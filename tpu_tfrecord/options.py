"""Typed, validated options — replacing the reference's stringly-typed map.

The reference threads a Map[String,String] from the DataFrame API and re-reads
``recordType`` independently at three sites with per-site validation
(DefaultSource.scala:35, TFRecordFileReader.scala:22,
TFRecordOutputWriter.scala:22). Here options are parsed and validated ONCE
into an immutable dataclass; being a plain picklable value it also plays the
role of the reference's SerializableConfiguration (DefaultSource.scala:145-182)
— the thing shipped from the coordinator to worker processes.
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass, replace
from typing import Any, Dict, Mapping, Optional

from tpu_tfrecord import wire
from tpu_tfrecord.schema import StructType


class RecordType(enum.Enum):
    EXAMPLE = "Example"
    SEQUENCE_EXAMPLE = "SequenceExample"
    BYTE_ARRAY = "ByteArray"

    @staticmethod
    def parse(value: "RecordType | str | None") -> "RecordType":
        """Parse with the reference's exact accepted spellings and default
        (``Example``; unknown value -> error, ref DefaultSource.scala:67-68)."""
        if value is None or value == "":
            return RecordType.EXAMPLE
        if isinstance(value, RecordType):
            return value
        for rt in RecordType:
            if rt.value == value:
                return rt
        raise ValueError(
            f"Unsupported recordType {value}: recordType can be ByteArray, "
            "Example or SequenceExample"
        )


@dataclass(frozen=True)
class TFRecordOptions:
    """All knobs for a read or write, validated at construction.

    Attributes mirror the reference's option vocabulary (README.md "Features"):
      - record_type: Example | SequenceExample | ByteArray
      - codec: None | 'gzip' | 'deflate' (write-side; read infers by extension)
      - schema: optional user-provided StructType (skips inference)
    plus TPU-native additions:
      - verify_crc: validate record CRCs on read
      - infer_sample_limit: cap records scanned per file during schema
        inference (the reference scans a whole file, README.md:73-74 calls the
        extra pass "expensive" — this bounds it; None = full file parity).
      - write_workers: encode/compress worker threads for the write pipeline
        (1 = the sequential legacy path, byte-identical to older releases).
      - num_shards: round-robin the output of one task over this many shard
        streams per partition directory (the reference gets multi-file output
        from Spark task parallelism; here one task drives N streams). Setting
        it engages the slab pipeline even at write_workers=1 so output bytes
        are a function of the data and options, never the worker count.
      - max_records_per_shard: rotate to a new shard file once a stream has
        written this many records (the option-level spelling of the writer's
        ``max_records_per_file`` constructor argument).
      - on_corrupt: read-side corruption policy. ``"raise"`` (default)
        propagates TFRecordCorruptionError exactly as before;
        ``"skip_record"`` resyncs past each bad frame (wire.resync) and
        keeps every salvageable record, bounded per shard by
        ``max_corrupt_records``; ``"skip_shard"`` drops the rest of a shard
        at its first corruption and keeps the epoch going.
      - max_corrupt_records: per-shard quota of corrupt regions tolerated
        under ``on_corrupt="skip_record"`` (None = unlimited). Quota
        exhausted escalates to ``corrupt_fallback``.
      - corrupt_fallback: what quota exhaustion escalates to —
        ``"raise"`` (default) or ``"skip_shard"``.
      - write_retries: transient-fault retries for commit-side filesystem
        ops (shard open, rename into place, _SUCCESS marker) — the
        option-level spelling of the writer's RetryPolicy.
      - read_deadline_ms: per-read deadline for shard byte reads (None =
        off). A read that exceeds it is converted into a raising
        DeadlineError (an OSError: it flows through read retries), counted
        in ``read.stalls``/``read.deadline_misses``.
      - open_deadline_ms: same deadline model for the shard OPEN call.
      - hedge_after_ms: straggler hedging — when a read has produced
        nothing for this long, a backup open+read of the same byte range
        launches; first result wins (byte-identical either way), the loser
        is cancelled. Counted in ``read.hedges``/``read.hedge_wins``.
      - on_stall: what an unrecoverable stall (deadline miss after
        retries, or a watchdog-detected wedged worker) does to the epoch:
        ``"raise"`` (default) propagates; ``"skip_shard"`` drops the rest
        of the stalled shard (counted in ``read.skipped_shards``, same
        deterministic accounting as ``on_corrupt="skip_shard"``) and the
        epoch continues.
      - watchdog_timeout_ms: per-dataset pipeline watchdog (None = off) —
        a parallel-read shard worker that makes no progress heartbeat for
        this long is declared wedged: its shard fails with a WatchdogError
        (handled per ``on_stall``) and a replacement worker is spawned
        (``read.watchdog_restarts``) so the rest of the epoch keeps
        decoding instead of blocking on the dead worker's queue forever.
      - cache: columnar epoch cache mode. ``"off"`` (default) decodes
        every epoch; ``"auto"`` appends each shard's decoded chunks to a
        per-shard cache entry on the first pass and serves later epochs
        (and later runs with the same decode fingerprint) as zero-copy
        mmap views — no frame parse, no CRC, no protobuf decode. A
        corrupt or stale entry falls back to the ground-truth TFRecord
        decode and is rewritten (tpu_tfrecord.cache).
      - cache_dir: where cache entries live (default: a per-USER
        directory under the system temp dir — uid-suffixed so one user's
        predictable entry names cannot be pre-staged by another). Must be
        a LOCAL path — the serve path mmaps entry files.
      - cache_max_bytes: LRU budget for ``cache_dir`` (None = unbounded);
        oldest-unused entries are evicted after each populate commit
        (``cache.evictions``).
      - trace: flight-recorder span tracing (tpu_tfrecord.telemetry).
        ``"off"`` (default) records nothing and pays one attribute read
        per would-be span; ``"on"`` records begin/end/thread/attrs for
        every pipeline op (open, read, decode, cache.serve,
        write.encode/compress/io, batch, stall/hedge/retry events) into a
        bounded ring buffer exportable as Chrome trace-event JSON
        (Perfetto-loadable). The recorder is process-global: any dataset
        or writer constructed with ``trace="on"`` enables it.
      - pulse_interval_s: emit one machine-parseable telemetry JSON line
        per interval while an iterator is live (stage throughputs,
        counters, gauges, histogram quantiles, and the producer/consumer
        bound-ness verdict). None (default) = no pulse.
      - telemetry_port: serve a Prometheus text endpoint (``/metrics``)
        on 127.0.0.1:PORT via a stdlib HTTP daemon thread (0 = an
        ephemeral port). None (default) = no endpoint.
      - telemetry_spool_dir: cluster telemetry spool (tpu_tfrecord.fleet).
        When set, this process periodically snapshots its counters,
        gauges, and histogram buckets (plus a heartbeat) into one
        atomically-rewritten JSONL file per process under this directory;
        a TelemetryAggregator / ``tfrecord_doctor fleet`` merges every
        process's spool into cluster-level counters, exact cluster
        quantiles, a dead-process list, and one federated Prometheus
        page. None (default) = no spool, zero new work on the hot path.
        Point every process of one job (decode workers, trainers, the
        dispatcher) at the SAME directory.
      - spool_interval_s: snapshot/heartbeat cadence for the spool
        (default 1.0s when ``telemetry_spool_dir`` is set). The
        aggregator's default staleness bar is 2x this interval.
      - telemetry_role: role label this process stamps on its pulse
        lines, spool snapshots, and merged-trace track names (e.g.
        ``"reader"``, ``"decode_worker"``, ``"trainer"``). Default: the
        process's current trace-context role (``"main"`` unless a parent
        propagated one). The ``"trainer"`` role is what the training
        flight recorder spools under (examples/_harness.trainer_spool —
        ``tfrecord_doctor train`` reports those processes' step-phase
        shares + verdict, and the elastic dispatcher's
        ``--scaler-roles trainer`` scopes its fleet verdict to them).
      - autotune: closed-loop knob tuning (tpu_tfrecord.autotune).
        ``"off"`` (default) keeps every knob static; ``"on"`` runs a
        controller at pulse boundaries that resizes the decode worker
        pool and prefetch queue from the producer/consumer bound-ness
        verdict, retargets readahead from observed IO bandwidth, and
        derives hedge/deadline thresholds from observed open/read p99 —
        with hysteresis, per-knob clamps, and a cooldown. Row output and
        checkpoint/resume stay byte-identical to any fixed-knob run.
      - autotune_interval_s: the controller's cadence when ``autotune``
        is on and no ``pulse_interval_s`` is set (default 1.0s; a
        configured pulse interval wins — the controller always runs at
        pulse boundaries).
      - service: disaggregated data service (tpu_tfrecord.service).
        ``"host:port"`` of the dispatcher — or a full partition-map spec
        (``"h:p1|h:p2,h:p3"``: comma-separated partitions, each
        ``primary|standby``; or ``"@map.json"``) — makes this dataset's
        iterators fetch decoded chunks from leased decode-worker
        processes instead of decoding locally; under a partition map the
        dataset routes to the partition owning its tenant digest and
        fails over to the standby. Batches, checkpoints, and shuffling
        are byte-identical either way (the service is an alternative
        chunk source under the same pipeline). None (default) = decode
        locally.
      - service_lease_ttl_s: dispatcher-side lease TTL — a worker whose
        heartbeat is older than this loses its leases and its shards are
        reassigned. Consumed by the dispatcher (``python -m
        tpu_tfrecord.service dispatcher`` defaults its ``--lease-ttl-s``
        from this option's default); carried here so the whole failure
        model is configured in one vocabulary. Consumers use it only as
        the suspect-aging default until the first route reply carries the
        dispatcher's REAL TTL, which then wins — a mis-set local value
        cannot desynchronize the client from the fleet's actual
        reassignment clock.
      - service_deadline_ms: consumer-side per-socket-op deadline
        (connect, request, each recv). A worker or dispatcher that
        produces nothing for this long is treated as dead for THIS
        attempt: the consumer re-routes (excluding the silent worker) and
        resumes from its acked offset.
      - service_fallback_ms: how long a shard may make NO progress through
        the service (across reconnects and re-routes) before the consumer
        degrades to a direct local read of the same shard — byte-identical
        rows, counted in ``service.fallbacks``. After a fallback, later
        shards probe the service with one quick attempt until it heals.
        None = never fall back (retry forever).
      - elastic_min_workers / elastic_max_workers / elastic_interval_s:
        the elastic decode fleet's floor, ceiling, and decision cadence
        (tpu_tfrecord.elastic.FleetScaler). Like ``service_lease_ttl_s``
        these are consumed by the dispatcher side (``python -m
        tpu_tfrecord.service dispatcher --elastic`` defaults its flags
        from them) — carried here so the whole elastic-fleet vocabulary
        is configured and validated in one place. ``elastic_max_workers``
        None defers to the scaler's policy default;
        ``elastic_interval_s`` None defers to the scaler's default
        cadence (1s).
    """

    record_type: RecordType = RecordType.EXAMPLE
    codec: Optional[str] = None
    schema: Optional[StructType] = None
    verify_crc: bool = True
    infer_sample_limit: Optional[int] = None
    write_workers: int = 1
    num_shards: Optional[int] = None
    max_records_per_shard: Optional[int] = None
    on_corrupt: str = "raise"
    max_corrupt_records: Optional[int] = 100
    corrupt_fallback: str = "raise"
    write_retries: int = 0
    read_deadline_ms: Optional[float] = None
    open_deadline_ms: Optional[float] = None
    hedge_after_ms: Optional[float] = None
    on_stall: str = "raise"
    watchdog_timeout_ms: Optional[float] = None
    cache: str = "off"
    cache_dir: Optional[str] = None
    cache_max_bytes: Optional[int] = None
    trace: str = "off"
    pulse_interval_s: Optional[float] = None
    telemetry_port: Optional[int] = None
    telemetry_spool_dir: Optional[str] = None
    spool_interval_s: Optional[float] = None
    telemetry_role: Optional[str] = None
    autotune: str = "off"
    autotune_interval_s: Optional[float] = None
    service: Optional[str] = None
    service_lease_ttl_s: float = 10.0
    service_deadline_ms: float = 5000.0
    service_fallback_ms: Optional[float] = 30000.0
    elastic_min_workers: int = 1
    elastic_max_workers: Optional[int] = None
    elastic_interval_s: Optional[float] = None

    _KNOWN_KEYS = (
        "recordType",
        "record_type",
        "codec",
        "schema",
        "verify_crc",
        "verifyCrc",
        "infer_sample_limit",
        "inferSampleLimit",
        "write_workers",
        "writeWorkers",
        "num_shards",
        "numShards",
        "max_records_per_shard",
        "maxRecordsPerShard",
        "on_corrupt",
        "onCorrupt",
        "max_corrupt_records",
        "maxCorruptRecords",
        "corrupt_fallback",
        "corruptFallback",
        "write_retries",
        "writeRetries",
        "read_deadline_ms",
        "readDeadlineMs",
        "open_deadline_ms",
        "openDeadlineMs",
        "hedge_after_ms",
        "hedgeAfterMs",
        "on_stall",
        "onStall",
        "watchdog_timeout_ms",
        "watchdogTimeoutMs",
        "cache",
        "cache_dir",
        "cacheDir",
        "cache_max_bytes",
        "cacheMaxBytes",
        "trace",
        "pulse_interval_s",
        "pulseIntervalS",
        "telemetry_port",
        "telemetryPort",
        "telemetry_spool_dir",
        "telemetrySpoolDir",
        "spool_interval_s",
        "spoolIntervalS",
        "telemetry_role",
        "telemetryRole",
        "autotune",
        "autotune_interval_s",
        "autotuneIntervalS",
        "service",
        "service_lease_ttl_s",
        "serviceLeaseTtlS",
        "service_deadline_ms",
        "serviceDeadlineMs",
        "service_fallback_ms",
        "serviceFallbackMs",
        "elastic_min_workers",
        "elasticMinWorkers",
        "elastic_max_workers",
        "elasticMaxWorkers",
        "elastic_interval_s",
        "elasticIntervalS",
    )

    ON_CORRUPT_POLICIES = ("raise", "skip_record", "skip_shard")
    CORRUPT_FALLBACKS = ("raise", "skip_shard")
    ON_STALL_POLICIES = ("raise", "skip_shard")
    CACHE_MODES = ("off", "auto")
    TRACE_MODES = ("off", "on")
    AUTOTUNE_MODES = ("off", "on")

    @staticmethod
    def from_map(options: Optional[Mapping[str, Any]] = None, **kwargs: Any) -> "TFRecordOptions":
        """Build from a string-keyed map, accepting the reference's spellings
        (``recordType``, ``codec``) as well as snake_case. Unknown keys raise:
        a config typo (``codec_=``, ``verifyCRC``) must fail loudly, never
        silently change behavior — the same principle the decoder options
        already enforce (io/dataset.py)."""
        merged: Dict[str, Any] = dict(options or {})
        merged.update(kwargs)
        record_type = RecordType.parse(
            merged.pop("recordType", merged.pop("record_type", None))
        )
        codec = wire.normalize_codec(merged.pop("codec", None))
        schema = merged.pop("schema", None)
        if isinstance(schema, (str, dict)):
            schema = StructType.from_json(schema)
        verify_crc = _parse_bool(merged.pop("verify_crc", merged.pop("verifyCrc", True)))
        limit = merged.pop("infer_sample_limit", merged.pop("inferSampleLimit", None))
        if limit is not None:
            limit = int(limit)
            if limit <= 0:
                raise ValueError("infer_sample_limit must be positive")
        write_workers = int(
            merged.pop("write_workers", merged.pop("writeWorkers", 1))
        )
        if write_workers < 1:
            raise ValueError("write_workers must be >= 1")
        num_shards = merged.pop("num_shards", merged.pop("numShards", None))
        if num_shards is not None:
            num_shards = int(num_shards)
            if num_shards < 1:
                raise ValueError("num_shards must be >= 1")
        max_per_shard = merged.pop(
            "max_records_per_shard", merged.pop("maxRecordsPerShard", None)
        )
        if max_per_shard is not None:
            max_per_shard = int(max_per_shard)
            if max_per_shard < 1:
                raise ValueError("max_records_per_shard must be >= 1")
        on_corrupt = str(
            merged.pop("on_corrupt", merged.pop("onCorrupt", "raise"))
        ).strip().lower()
        if on_corrupt not in TFRecordOptions.ON_CORRUPT_POLICIES:
            raise ValueError(
                f"on_corrupt must be one of {TFRecordOptions.ON_CORRUPT_POLICIES}, "
                f"got {on_corrupt!r}"
            )
        max_corrupt = merged.pop(
            "max_corrupt_records", merged.pop("maxCorruptRecords", 100)
        )
        if max_corrupt is not None:
            max_corrupt = int(max_corrupt)
            if max_corrupt < 0:
                raise ValueError("max_corrupt_records must be >= 0 (or None)")
        corrupt_fallback = str(
            merged.pop("corrupt_fallback", merged.pop("corruptFallback", "raise"))
        ).strip().lower()
        if corrupt_fallback not in TFRecordOptions.CORRUPT_FALLBACKS:
            raise ValueError(
                f"corrupt_fallback must be one of "
                f"{TFRecordOptions.CORRUPT_FALLBACKS}, got {corrupt_fallback!r}"
            )
        write_retries = int(
            merged.pop("write_retries", merged.pop("writeRetries", 0))
        )
        if write_retries < 0:
            raise ValueError("write_retries must be >= 0")

        def _pos_ms(snake: str, camel: str) -> Optional[float]:
            v = merged.pop(snake, merged.pop(camel, None))
            if v is None:
                return None
            v = float(v)
            if v <= 0:
                raise ValueError(f"{snake} must be > 0 (or None)")
            return v

        read_deadline_ms = _pos_ms("read_deadline_ms", "readDeadlineMs")
        open_deadline_ms = _pos_ms("open_deadline_ms", "openDeadlineMs")
        hedge_after_ms = _pos_ms("hedge_after_ms", "hedgeAfterMs")
        watchdog_timeout_ms = _pos_ms("watchdog_timeout_ms", "watchdogTimeoutMs")
        on_stall = str(
            merged.pop("on_stall", merged.pop("onStall", "raise"))
        ).strip().lower()
        if on_stall not in TFRecordOptions.ON_STALL_POLICIES:
            raise ValueError(
                f"on_stall must be one of {TFRecordOptions.ON_STALL_POLICIES}, "
                f"got {on_stall!r}"
            )
        cache = str(merged.pop("cache", "off") or "off").strip().lower()
        if cache not in TFRecordOptions.CACHE_MODES:
            raise ValueError(
                f"cache must be one of {TFRecordOptions.CACHE_MODES}, "
                f"got {cache!r}"
            )
        cache_dir = merged.pop("cache_dir", merged.pop("cacheDir", None))
        if cache_dir is not None:
            cache_dir = os.fspath(cache_dir)
        cache_max_bytes = merged.pop(
            "cache_max_bytes", merged.pop("cacheMaxBytes", None)
        )
        if cache_max_bytes is not None:
            cache_max_bytes = int(cache_max_bytes)
            if cache_max_bytes < 1:
                raise ValueError("cache_max_bytes must be >= 1 (or None)")
        trace = str(merged.pop("trace", "off") or "off").strip().lower()
        if trace not in TFRecordOptions.TRACE_MODES:
            raise ValueError(
                f"trace must be one of {TFRecordOptions.TRACE_MODES}, "
                f"got {trace!r}"
            )
        pulse_interval_s = merged.pop(
            "pulse_interval_s", merged.pop("pulseIntervalS", None)
        )
        if pulse_interval_s is not None:
            pulse_interval_s = float(pulse_interval_s)
            if pulse_interval_s <= 0:
                raise ValueError("pulse_interval_s must be > 0 (or None)")
        telemetry_port = merged.pop(
            "telemetry_port", merged.pop("telemetryPort", None)
        )
        if telemetry_port is not None:
            telemetry_port = int(telemetry_port)
            if not 0 <= telemetry_port <= 65535:
                raise ValueError(
                    "telemetry_port must be in [0, 65535] (0 = ephemeral)"
                )
        telemetry_spool_dir = merged.pop(
            "telemetry_spool_dir", merged.pop("telemetrySpoolDir", None)
        )
        if telemetry_spool_dir is not None:
            telemetry_spool_dir = os.fspath(telemetry_spool_dir)
        spool_interval_s = merged.pop(
            "spool_interval_s", merged.pop("spoolIntervalS", None)
        )
        if spool_interval_s is not None:
            spool_interval_s = float(spool_interval_s)
            if spool_interval_s <= 0:
                raise ValueError("spool_interval_s must be > 0 (or None)")
        telemetry_role = merged.pop(
            "telemetry_role", merged.pop("telemetryRole", None)
        )
        if telemetry_role is not None:
            telemetry_role = str(telemetry_role)
            if not telemetry_role:
                raise ValueError("telemetry_role must be non-empty (or None)")
        autotune = str(merged.pop("autotune", "off") or "off").strip().lower()
        if autotune not in TFRecordOptions.AUTOTUNE_MODES:
            raise ValueError(
                f"autotune must be one of {TFRecordOptions.AUTOTUNE_MODES}, "
                f"got {autotune!r}"
            )
        autotune_interval_s = merged.pop(
            "autotune_interval_s", merged.pop("autotuneIntervalS", None)
        )
        if autotune_interval_s is not None:
            autotune_interval_s = float(autotune_interval_s)
            if autotune_interval_s <= 0:
                raise ValueError("autotune_interval_s must be > 0 (or None)")
        service = merged.pop("service", None)
        if service is not None:
            service = str(service)
            from tpu_tfrecord.service import PartitionMap

            # loud on anything that is neither a host:port nor a
            # partition-map spec ("h:p1|h:p2,h:p3" / "@map.json")
            PartitionMap.parse(service)
        service_lease_ttl_s = float(
            merged.pop("service_lease_ttl_s", merged.pop("serviceLeaseTtlS", 10.0))
        )
        if service_lease_ttl_s <= 0:
            raise ValueError("service_lease_ttl_s must be > 0")
        service_deadline_ms = float(
            merged.pop(
                "service_deadline_ms", merged.pop("serviceDeadlineMs", 5000.0)
            )
        )
        if service_deadline_ms <= 0:
            raise ValueError("service_deadline_ms must be > 0")
        service_fallback_ms = merged.pop(
            "service_fallback_ms", merged.pop("serviceFallbackMs", 30000.0)
        )
        if service_fallback_ms is not None:
            service_fallback_ms = float(service_fallback_ms)
            if service_fallback_ms < 0:
                raise ValueError("service_fallback_ms must be >= 0 (or None)")
        elastic_min_workers = int(
            merged.pop("elastic_min_workers", merged.pop("elasticMinWorkers", 1))
        )
        if elastic_min_workers < 1:
            raise ValueError("elastic_min_workers must be >= 1")
        elastic_max_workers = merged.pop(
            "elastic_max_workers", merged.pop("elasticMaxWorkers", None)
        )
        if elastic_max_workers is not None:
            elastic_max_workers = int(elastic_max_workers)
            if elastic_max_workers < elastic_min_workers:
                raise ValueError(
                    "elastic_max_workers must be >= elastic_min_workers "
                    "(or None)"
                )
        elastic_interval_s = merged.pop(
            "elastic_interval_s", merged.pop("elasticIntervalS", None)
        )
        if elastic_interval_s is not None:
            elastic_interval_s = float(elastic_interval_s)
            if elastic_interval_s <= 0:
                raise ValueError("elastic_interval_s must be > 0 (or None)")
        if merged:
            import difflib

            hints = []
            for key in merged:
                close = difflib.get_close_matches(
                    str(key), TFRecordOptions._KNOWN_KEYS, n=1
                )
                hints.append(
                    f"{key!r}" + (f" (did you mean {close[0]!r}?)" if close else "")
                )
            raise ValueError(
                f"Unknown option(s): {', '.join(hints)}. Supported options: "
                + ", ".join(TFRecordOptions._KNOWN_KEYS)
            )
        return TFRecordOptions(
            record_type=record_type,
            codec=codec,
            schema=schema,
            verify_crc=verify_crc,
            infer_sample_limit=limit,
            write_workers=write_workers,
            num_shards=num_shards,
            max_records_per_shard=max_per_shard,
            on_corrupt=on_corrupt,
            max_corrupt_records=max_corrupt,
            corrupt_fallback=corrupt_fallback,
            write_retries=write_retries,
            read_deadline_ms=read_deadline_ms,
            open_deadline_ms=open_deadline_ms,
            hedge_after_ms=hedge_after_ms,
            on_stall=on_stall,
            watchdog_timeout_ms=watchdog_timeout_ms,
            cache=cache,
            cache_dir=cache_dir,
            cache_max_bytes=cache_max_bytes,
            trace=trace,
            pulse_interval_s=pulse_interval_s,
            telemetry_port=telemetry_port,
            telemetry_spool_dir=telemetry_spool_dir,
            spool_interval_s=spool_interval_s,
            telemetry_role=telemetry_role,
            autotune=autotune,
            autotune_interval_s=autotune_interval_s,
            service=service,
            service_lease_ttl_s=service_lease_ttl_s,
            service_deadline_ms=service_deadline_ms,
            service_fallback_ms=service_fallback_ms,
            elastic_min_workers=elastic_min_workers,
            elastic_max_workers=elastic_max_workers,
            elastic_interval_s=elastic_interval_s,
        )

    def with_schema(self, schema: StructType) -> "TFRecordOptions":
        return replace(self, schema=schema)

    def file_extension(self) -> str:
        """'.tfrecord' + codec suffix (ref DefaultSource.scala:112-114)."""
        return ".tfrecord" + wire.codec_extension(self.codec)


def _parse_bool(value: Any) -> bool:
    if isinstance(value, bool):
        return value
    if isinstance(value, str):
        return value.strip().lower() in ("1", "true", "yes")
    return bool(value)
