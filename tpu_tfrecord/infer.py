"""Schema inference with the numeric-precedence type lattice.

TPU-native re-implementation of reference TensorFlowInferSchema.scala:26-229:

1. Infer a type per feature per record (empty list -> "null type"; length 1 ->
   scalar; length > 1 -> array; TensorFlowInferSchema.scala:147-188).
2. Merge per-record maps with the tightest common type by numeric precedence
   Long < Float < String < Array(Long) < ... < Array(Array(String))
   (TensorFlowInferSchema.scala:194-228).
3. Fields still null-typed at the end become NullType columns
   (TensorFlowInferSchema.scala:48-57).

SequenceExample FeatureLists reduce their inner Features' types and wrap to
Array(Array(t)) (TensorFlowInferSchema.scala:98-118).

Where the reference runs this as a Spark RDD ``aggregate`` (per-partition
seqOp on executors + combOp tree-merge on the driver,
TensorFlowInferSchema.scala:40-43), the TPU-native version exposes the same
algebra as plain functions: ``infer_from_records`` is the seqOp loop,
``merge_type_maps`` the combOp — reused verbatim by the multi-host path
(tpu_tfrecord.tpu.distributed) where per-host partial maps are merged on
host 0 over the jax.distributed client.

Field order: the reference inherits JVM HashMap iteration order (arbitrary);
we emit fields sorted by name for determinism across hosts.
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping, Optional, Union

from tpu_tfrecord import proto
from tpu_tfrecord.proto import BYTES_LIST, FLOAT_LIST, INT64_LIST, Example, Feature, SequenceExample
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DataType,
    FloatType,
    LongType,
    NullType,
    StringType,
    StructField,
    StructType,
)

# A "null" inferred type is represented as Python None, like the reference's
# Scala nulls inside the aggregation maps.
TypeMap = Dict[str, Optional[DataType]]

_LONG = LongType()
_FLOAT = FloatType()
_STRING = StringType()


class SchemaInferenceError(ValueError):
    pass


def infer_field(feature: Feature) -> Optional[DataType]:
    """Infer one Feature's type (ref TensorFlowInferSchema.scala:132-188)."""
    n = len(feature.values)
    if feature.kind == BYTES_LIST:
        base: DataType = _STRING
    elif feature.kind == INT64_LIST:
        base = _LONG
    elif feature.kind == FLOAT_LIST:
        base = _FLOAT
    else:
        raise SchemaInferenceError("unsupported feature kind (oneof unset)")
    if n == 0:
        return None
    if n > 1:
        return ArrayType(base)
    return base


def _precedence(dtype: DataType) -> int:
    """The lattice (ref TensorFlowInferSchema.scala:194-207)."""
    if dtype == _LONG:
        return 1
    if dtype == _FLOAT:
        return 2
    if dtype == _STRING:
        return 3
    if isinstance(dtype, ArrayType):
        elem = dtype.element_type
        if elem == _LONG:
            return 4
        if elem == _FLOAT:
            return 5
        if elem == _STRING:
            return 6
        if isinstance(elem, ArrayType):
            inner = elem.element_type
            if inner == _LONG:
                return 7
            if inner == _FLOAT:
                return 8
            if inner == _STRING:
                return 9
    raise SchemaInferenceError(f"Unable to get the precedence for datatype {dtype}")


def find_tightest_common_type(
    t1: Optional[DataType], t2: Optional[DataType]
) -> Optional[DataType]:
    """Tightest common type; None (null) yields the other side
    (ref TensorFlowInferSchema.scala:213-228)."""
    if t1 == t2:
        return t1
    if t1 is None:
        return t2
    if t2 is None:
        return t1
    return t1 if _precedence(t1) > _precedence(t2) else t2


def _update(acc: TypeMap, name: str, current: Optional[DataType]) -> None:
    if name in acc:
        acc[name] = find_tightest_common_type(acc[name], current)
    else:
        acc[name] = current


def infer_example_row_type(acc: TypeMap, example: Example) -> TypeMap:
    for name, feature in example.features.items():
        _update(acc, name, infer_field(feature))
    return acc


def infer_sequence_example_row_type(acc: TypeMap, se: SequenceExample) -> TypeMap:
    for name, feature in se.context.items():
        _update(acc, name, infer_field(feature))
    for name, flist in se.feature_lists.items():
        if not flist.feature:
            _update(acc, name, None)
            continue
        inner: Optional[DataType] = None
        first = True
        for f in flist.feature:
            t = infer_field(f)
            inner = t if first else find_tightest_common_type(inner, t)
            first = False
        if inner is None:
            # All inner features empty: the whole FeatureList is "null" so far.
            _update(acc, name, None)
        elif isinstance(inner, ArrayType):
            _update(acc, name, ArrayType(inner))
        else:
            _update(acc, name, ArrayType(ArrayType(inner)))
    return acc


# Precedence -> type, the inverse of _precedence (index == precedence).
# Shared with the native inference seqOp (tfr_infer_batch), whose per-shard
# output is a (name -> max precedence) map in exactly this encoding.
_PREC_TYPES = [
    None,
    _LONG,
    _FLOAT,
    _STRING,
    ArrayType(_LONG),
    ArrayType(_FLOAT),
    ArrayType(_STRING),
    ArrayType(ArrayType(_LONG)),
    ArrayType(ArrayType(_FLOAT)),
    ArrayType(ArrayType(_STRING)),
]


def type_map_from_precedences(precs: Mapping[str, int]) -> TypeMap:
    """Native seqOp partial (name -> max precedence 0..9) -> TypeMap.
    Valid because the lattice merge IS precedence max with null identity
    (find_tightest_common_type), so the max commutes with per-record folds."""
    return {name: _PREC_TYPES[p] for name, p in precs.items()}


def merge_type_maps(first: TypeMap, second: TypeMap) -> TypeMap:
    """The combOp: key union + tightest common type. Like the reference's
    ``.get`` on the Option (TensorFlowInferSchema.scala:124), merging two
    *incompatible* concrete types raises (SURVEY.md §3.3 quirk)."""
    merged: TypeMap = {}
    for key in first.keys() | second.keys():
        merged[key] = find_tightest_common_type(first.get(key), second.get(key))
    return merged


def type_map_to_schema(acc: Mapping[str, Optional[DataType]]) -> StructType:
    fields = [
        StructField(name, NullType() if dtype is None else dtype, nullable=True)
        for name, dtype in sorted(acc.items())
    ]
    return StructType(fields)


def infer_from_records(
    records: Iterable[bytes],
    record_type,
    limit: Optional[int] = None,
) -> TypeMap:
    """seqOp loop over serialized record bytes (one shard's partial map)."""
    from tpu_tfrecord.options import RecordType

    acc: TypeMap = {}
    count = 0
    if record_type == RecordType.EXAMPLE:
        for data in records:
            infer_example_row_type(acc, proto.parse_example(data))
            count += 1
            if limit is not None and count >= limit:
                break
    elif record_type == RecordType.SEQUENCE_EXAMPLE:
        for data in records:
            infer_sequence_example_row_type(acc, proto.parse_sequence_example(data))
            count += 1
            if limit is not None and count >= limit:
                break
    else:
        raise SchemaInferenceError(
            "Unsupported recordType: recordType can be Example or SequenceExample"
        )
    return acc


def infer_schema(
    records: Iterable[Union[bytes, Example, SequenceExample]],
    record_type=None,
    limit: Optional[int] = None,
) -> StructType:
    """Infer a StructType from records (bytes or parsed messages).

    The ByteArray record type has a fixed single-column schema
    (ref TensorFlowInferSchema.scala:60-64).
    """
    from tpu_tfrecord.options import RecordType

    record_type = RecordType.parse(record_type) if not isinstance(record_type, RecordType) else record_type
    if record_type == RecordType.BYTE_ARRAY:
        return byte_array_schema()

    acc: TypeMap = {}
    count = 0
    for rec in records:
        if isinstance(rec, (bytes, bytearray, memoryview)):
            rec = (
                proto.parse_example(bytes(rec))
                if record_type == RecordType.EXAMPLE
                else proto.parse_sequence_example(bytes(rec))
            )
        if record_type == RecordType.EXAMPLE:
            if not isinstance(rec, Example):
                raise SchemaInferenceError(f"expected Example, got {type(rec).__name__}")
            infer_example_row_type(acc, rec)
        else:
            if not isinstance(rec, SequenceExample):
                raise SchemaInferenceError(
                    f"expected SequenceExample, got {type(rec).__name__}"
                )
            infer_sequence_example_row_type(acc, rec)
        count += 1
        if limit is not None and count >= limit:
            break
    return type_map_to_schema(acc)


def byte_array_schema() -> StructType:
    """ref TensorFlowInferSchema.scala:60-64."""
    return StructType([StructField("byteArray", BinaryType())])
