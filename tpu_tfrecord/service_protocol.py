"""Wire protocol for the disaggregated data service (tpu_tfrecord.service).

Everything that crosses a socket between a trainer consumer, a decode
worker, and the dispatcher goes through this module, so the framing,
integrity, and fault-injection story has ONE owner:

- **Control frames**: ``u32 payload_len | u32 masked_crc32c(payload) |
  payload`` where the payload is one JSON object (the same masked-CRC
  recipe as the TFRecord file format, ``wire.masked_crc32c``). A frame
  whose CRC does not match, whose declared length is absurd, or whose
  connection closes mid-frame raises :class:`ProtocolError` — a
  ``ConnectionError`` subclass, so every client-side reconnect/fallback
  net that catches ``OSError`` already handles it.

- **Chunk bodies**: a decoded ``ColumnarBatch`` chunk travels as a control
  frame (the chunk header: start offset, row count, per-column section
  table with dtype/shape/nbytes/CRC32C per buffer) followed by the raw
  concatenated section bytes. The section layout and per-section CRCs are
  the SAME primitives the columnar epoch cache serializes entries with
  (``cache.column_buffers`` / ``cache.section_crc``), so the two
  serializers cannot drift; receive-side reconstruction mirrors
  ``CachedShard.chunk_batch``.

- **Chaos seam**: ``install_chaos`` (tpu_tfrecord.faults) points
  ``_CHAOS_PLAN`` at a seeded :class:`~tpu_tfrecord.faults.FaultPlan`;
  every ``connect`` and every ``recv`` then consults the plan
  (refused-connection errors, bounded stalls, capped recvs, mid-frame
  disconnects), with every fired fault in the same replayable ledger as
  the file-seam faults. ``_CHAOS_PLAN is None`` (the default) costs one
  module-global read per call.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tpu_tfrecord import wire
from tpu_tfrecord.columnar import Column, ColumnarBatch

#: bumped on any incompatible frame/message change; peers reject mismatches
#: loudly instead of mis-parsing each other.
PROTO_VERSION = 1

_FRAME = struct.Struct("<II")  # payload length, masked crc32c(payload)

#: a control frame is JSON — anything near this size is corruption, not a
#: message (chunk BODIES are not frames; they are length-driven raw bytes).
MAX_CONTROL_FRAME = 64 << 20

#: chunk bodies are slab-scale; a header announcing anything outside
#: [0, this] is a corrupt/hostile length field and is rejected BEFORE the
#: receive buffer is allocated.
MAX_CHUNK_BODY = 4 << 30

#: set by faults.install_chaos for the duration of a chaos block.
_CHAOS_PLAN = None


class ProtocolError(ConnectionError):
    """A peer spoke garbage: short frame, CRC mismatch, absurd length,
    version skew, or a malformed message. ConnectionError so transport
    retry nets treat it as 'this connection is dead', never as data."""


def parse_addr(addr: str) -> Tuple[str, int]:
    """``"host:port"`` -> (host, port), validated loudly."""
    host, sep, port = str(addr).rpartition(":")
    if not sep or not host:
        raise ValueError(f"service address must be 'host:port', got {addr!r}")
    return host, int(port)


def format_addr(host: str, port: int) -> str:
    return f"{host}:{port}"


def _apply_chaos(op: str, addr: str, sock=None, size: Optional[int] = None):
    plan = _CHAOS_PLAN
    if plan is None:
        return None
    return plan.apply_socket(op, addr, sock=sock, size=size)


def enable_nodelay(sock: socket.socket) -> None:
    """Disable Nagle. Both SIDES of a framed request/reply stream need
    this: ``send_frame`` is two sendalls (header, payload), and a
    Nagle'd second segment waits out the peer's delayed ACK (~40ms) —
    per RPC. Accepted server conns are where that bite was measured
    (route+shard_done pairs went 45/s -> thousands/s)."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # non-TCP transports (tests) — latency hint only


def connect(addr: str, timeout: Optional[float]) -> socket.socket:
    """Open a TCP connection to ``addr`` under the chaos plan (refused /
    stalled connects fire here) with ``timeout`` as both the connect and
    the per-op socket timeout."""
    host, port = parse_addr(addr)
    _apply_chaos("connect", addr)
    sock = socket.create_connection((host, port), timeout=timeout)
    enable_nodelay(sock)
    return sock


def _recv_exact(
    sock: socket.socket, n: int, addr: str, allow_eof: bool = False
) -> Optional[bytearray]:
    """Read exactly ``n`` bytes. A clean close at a frame boundary returns
    None when ``allow_eof`` (end of a message stream); a close anywhere
    else is a short frame -> ProtocolError. Chaos recv rules (stall, cap,
    disconnect) apply per recv call."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        want = n - got
        cap = _apply_chaos("recv", addr, sock=sock, size=want)
        if cap is not None and cap < want:
            want = cap
        try:
            k = sock.recv_into(view[got : got + want])
        except socket.timeout as e:
            raise TimeoutError(f"recv timed out talking to {addr}") from e
        if k == 0:
            if got == 0 and allow_eof:
                return None
            raise ProtocolError(
                f"short frame from {addr}: connection closed after "
                f"{got}/{n} bytes"
            )
        got += k
    return buf


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_FRAME.pack(len(payload), wire.masked_crc32c(payload)))
    sock.sendall(payload)


def recv_frame(
    sock: socket.socket, addr: str, allow_eof: bool = False
) -> Optional[bytes]:
    head = _recv_exact(sock, _FRAME.size, addr, allow_eof=allow_eof)
    if head is None:
        return None
    length, crc = _FRAME.unpack(bytes(head))
    if length > MAX_CONTROL_FRAME:
        raise ProtocolError(
            f"control frame of {length} bytes from {addr} exceeds "
            f"{MAX_CONTROL_FRAME} — corrupt length field?"
        )
    payload = bytes(_recv_exact(sock, length, addr))
    if wire.masked_crc32c(payload) != crc:
        raise ProtocolError(f"control frame CRC mismatch from {addr}")
    return payload


def send_msg(sock: socket.socket, obj: Dict[str, Any]) -> None:
    send_frame(sock, json.dumps(obj, sort_keys=True).encode("utf-8"))


def recv_msg(
    sock: socket.socket, addr: str, allow_eof: bool = False
) -> Optional[Dict[str, Any]]:
    payload = recv_frame(sock, addr, allow_eof=allow_eof)
    if payload is None:
        return None
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise ProtocolError(f"malformed message from {addr}: {e}") from e
    if not isinstance(obj, dict):
        raise ProtocolError(f"malformed message from {addr}: not an object")
    return obj


def request(sock: socket.socket, addr: str, obj: Dict[str, Any]) -> Dict[str, Any]:
    """One request/response round trip on a persistent connection."""
    send_msg(sock, obj)
    reply = recv_msg(sock, addr)
    if reply is None:
        raise ProtocolError(f"{addr} closed the connection mid-request")
    return reply


# ---------------------------------------------------------------------------
# Chunk serialization — the cache container's section layout, over a socket
# ---------------------------------------------------------------------------


def chunk_header(batch: ColumnarBatch, start: int, index: int) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """Build the chunk control message + the ordered section arrays whose
    raw bytes follow it. Column order is the DECODER's emission order and
    travels in the header: the receiver rebuilds in header order, so a
    service-fed batch has the same column order a local decode of the same
    job spec would produce (downstream batch assembly is order-sensitive)."""
    from tpu_tfrecord import cache as _cache

    cols = []
    arrs: List[np.ndarray] = []
    total = 0
    for name, col in batch.columns.items():
        sections = []
        for role, arr in _cache.column_buffers(col):
            sections.append(
                {
                    "role": role,
                    "dtype": arr.dtype.str,
                    "shape": list(arr.shape) if arr.ndim != 1 else None,
                    "nbytes": int(arr.nbytes),
                    "crc": _cache.section_crc(arr),
                }
            )
            arrs.append(arr)
            total += int(arr.nbytes)
        cm: Dict[str, Any] = {"name": name, "sections": sections}
        if col.hash_buckets is not None:
            cm["hash_buckets"] = int(col.hash_buckets)
        cols.append(cm)
    header = {
        "op": "chunk",
        "chunk": int(index),
        "start": int(start),
        "rows": int(batch.num_rows),
        "cols": cols,
        "body": total,
    }
    return header, arrs


def send_chunk(sock: socket.socket, batch: ColumnarBatch, start: int, index: int) -> int:
    """Frame + send one decoded chunk; returns the body byte count."""
    header, arrs = chunk_header(batch, start, index)
    send_msg(sock, header)
    for arr in arrs:
        sock.sendall(memoryview(np.ascontiguousarray(arr)).cast("B"))
    return header["body"]


def recv_chunk_body(
    sock: socket.socket, header: Dict[str, Any], addr: str, dtype_of, verify: bool = True
) -> ColumnarBatch:
    """Receive the raw section bytes a ``chunk`` message announced and
    rebuild the ColumnarBatch (mirrors CachedShard.chunk_batch: numpy views
    over one receive buffer; bytes-like blobs are the single copy).
    ``verify`` checks every section CRC32C against the header's stamps."""
    from tpu_tfrecord import cache as _cache

    try:
        total = int(header.get("body", 0))
    except (TypeError, ValueError) as e:
        raise ProtocolError(f"malformed chunk header from {addr}: {e}") from e
    if not 0 <= total <= MAX_CHUNK_BODY:
        raise ProtocolError(
            f"chunk body of {total} bytes from {addr} outside "
            f"[0, {MAX_CHUNK_BODY}] — corrupt length field?"
        )
    body = _recv_exact(sock, total, addr) if total else bytearray()
    off = 0
    cols: Dict[str, Column] = {}
    try:
        for cm in header["cols"]:
            name = cm["name"]
            col = Column(name, dtype_of(name), hash_buckets=cm.get("hash_buckets"))
            for sec in cm["sections"]:
                nb = int(sec["nbytes"])
                if off + nb > total:
                    raise ProtocolError(
                        f"chunk section overruns its body ({off}+{nb} > "
                        f"{total}) from {addr}"
                    )
                seg = np.frombuffer(body, dtype=np.uint8, count=nb, offset=off)
                if verify and _cache.section_crc(seg) != int(sec["crc"]):
                    raise ProtocolError(
                        f"chunk section CRC mismatch ({cm['name']}/"
                        f"{sec['role']}) from {addr}"
                    )
                role = sec["role"]
                if role == "blob":
                    col.blob = bytes(seg)
                else:
                    arr = seg.view(np.dtype(sec["dtype"]))
                    shape = sec.get("shape")
                    if shape is not None and len(shape) != 1:
                        arr = arr.reshape(shape)
                    setattr(col, role, arr)
                off += nb
            cols[name] = col
        rows = int(header["rows"])
    except (KeyError, TypeError, ValueError) as e:
        raise ProtocolError(f"malformed chunk header from {addr}: {e}") from e
    if off != total:
        raise ProtocolError(
            f"chunk body size mismatch from {addr}: sections cover {off} "
            f"of {total} bytes"
        )
    return ColumnarBatch(cols, rows)
