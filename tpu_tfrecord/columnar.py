"""Columnar batch decoding: serialized records -> numpy column buffers.

This is the TPU-native hot path. The reference materializes one
SpecificInternalRow per record (TFRecordFileReader.scala:46-82) because Spark
is a row engine; a TPU wants large dense device arrays, so here a batch of
serialized tf.Example records decodes STRAIGHT into per-column numpy buffers
— no per-record row objects, no per-field boxing:

- numeric scalar column  -> values[N] + validity mask[N]
- numeric array column   -> ragged: values[total] + offsets[N+1]
- array-of-array column  -> ragged^2: values[total] + inner_offsets[M+1]
                            + row_splits[N+1] (SequenceExample FeatureLists)
- string/binary columns  -> list of bytes (vocab/hashing happens host-side)

The same layout is produced by the C++ extension (tpu_tfrecord._native) at
>10x the throughput; this module is the pure-Python reference implementation
and the correctness oracle for it.

Ragged columns pad/bucket into dense [batch, max_len] arrays in
tpu_tfrecord.tpu.ingest — the "first-class ragged-sequence decode" plan of
SURVEY.md §5 (long-context story).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from tpu_tfrecord import proto
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DataType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    NullType,
    StringType,
    StructType,
    numpy_dtype,
)
from tpu_tfrecord.serde import NullValueError


class Column:
    """One decoded column. Exactly one of the layouts below is populated.

    - scalar numeric: ``values`` [N]
    - ragged numeric: ``values`` [total] + ``offsets`` [N+1]
    - ragged^2 numeric: ``values`` [total] + ``inner_offsets`` + ``offsets``
      (offsets indexes into inner_offsets: row i spans inner lists
      offsets[i]:offsets[i+1], inner list j spans values
      inner_offsets[j]:inner_offsets[j+1])
    - bytes-like: one flat ``blob`` buffer + ``blob_offsets`` [n_values+1]
      value boundaries (with the same offsets scheme above it) — per-value
      Python objects are only materialized on demand via ``blobs``.
    """

    __slots__ = ("name", "dtype", "values", "offsets", "inner_offsets",
                 "blob", "blob_offsets", "mask", "hash_buckets")

    def __init__(
        self,
        name: str,
        dtype: DataType,
        values: Optional[np.ndarray] = None,
        offsets: Optional[np.ndarray] = None,
        inner_offsets: Optional[np.ndarray] = None,
        blob: Optional[bytes] = None,
        blob_offsets: Optional[np.ndarray] = None,
        mask: Optional[np.ndarray] = None,
        hash_buckets: Optional[int] = None,
    ):
        self.name = name
        self.dtype = dtype
        self.values = values
        self.offsets = offsets
        self.inner_offsets = inner_offsets
        self.blob = blob
        self.blob_offsets = blob_offsets
        self.mask = mask  # validity per row
        # set when a bytes column was hash-fused during decode: the bucket
        # count its int32 values were computed with
        self.hash_buckets = hash_buckets

    @property
    def is_ragged(self) -> bool:
        return self.offsets is not None

    @property
    def is_bytes(self) -> bool:
        return self.blob is not None

    def row_lengths(self) -> np.ndarray:
        assert self.offsets is not None
        return np.diff(self.offsets)

    @property
    def blobs(self) -> Optional[List[bytes]]:
        """Materialize per-value bytes objects (view concern — the hot path
        works on the flat ``blob`` + ``blob_offsets`` arrays)."""
        if self.blob is None:
            return None
        bo = self.blob_offsets
        blob = self.blob
        return [bytes(blob[bo[j] : bo[j + 1]]) for j in range(len(bo) - 1)]

    def set_blobs(self, items: Sequence[bytes]) -> None:
        self.blob = b"".join(items)
        self.blob_offsets = np.concatenate(
            ([0], np.cumsum(np.fromiter((len(b) for b in items), dtype=np.int64,
                                        count=len(items))))
        ) if items else np.zeros(1, dtype=np.int64)


@dataclass
class ColumnarBatch:
    columns: Dict[str, Column]
    num_rows: int

    def __getitem__(self, name: str) -> Column:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns


def _is_bytes_like(dt: DataType) -> bool:
    return isinstance(dt, (StringType, BinaryType))


class _FieldAcc:
    """Per-field accumulator filled record by record."""

    __slots__ = (
        "name", "dtype", "np_dtype", "kind", "layout", "nullable",
        "values", "lengths", "inner_lengths", "blobs", "mask", "decode_str",
    )

    # layout: 'scalar' | 'ragged' | 'ragged2'
    def __init__(self, name: str, dtype: DataType, nullable: bool):
        self.name = name
        self.dtype = dtype
        self.nullable = nullable
        self.decode_str = False
        elem: DataType = dtype
        if isinstance(dtype, ArrayType):
            if isinstance(dtype.element_type, ArrayType):
                self.layout = "ragged2"
                elem = dtype.element_type.element_type
            else:
                self.layout = "ragged"
                elem = dtype.element_type
        else:
            self.layout = "scalar"
        if isinstance(elem, ArrayType):
            raise ValueError(f"column {name}: >2-level nesting unsupported")
        if isinstance(elem, NullType):
            self.kind = None
        elif isinstance(elem, (IntegerType, LongType)):
            self.kind = proto.INT64_LIST
        elif isinstance(elem, (FloatType, DoubleType, DecimalType)):
            self.kind = proto.FLOAT_LIST
        elif _is_bytes_like(elem):
            self.kind = proto.BYTES_LIST
            self.decode_str = False  # keep raw bytes; str decode is a view concern
        else:
            raise ValueError(f"column {name}: unsupported element type {elem}")
        self.np_dtype = numpy_dtype(dtype) if self.kind != proto.BYTES_LIST else None
        self.values: List = []
        self.lengths: List[int] = []
        self.inner_lengths: List[int] = []
        self.blobs: List[bytes] = []
        self.mask: List[bool] = []

    # -- per-record appends --------------------------------------------------

    def append_missing(self) -> None:
        if not self.nullable:
            raise NullValueError(f"Field {self.name} does not allow null values")
        self.mask.append(False)
        if self.layout == "scalar":
            if self.kind == proto.BYTES_LIST:
                self.blobs.append(b"")
            else:
                self.values.append(0)
        else:
            self.lengths.append(0)

    def append_feature(self, feature: proto.Feature) -> None:
        if feature.kind != self.kind:
            if feature.kind is None:
                self.append_missing()
                return
            raise ValueError(
                f"column {self.name}: feature kind {feature.kind_name} does not "
                f"match schema type {self.dtype}"
            )
        vals = feature.values
        self.mask.append(True)
        if self.layout == "scalar":
            if self.kind == proto.BYTES_LIST:
                self.blobs.append(vals[0] if len(vals) else b"")
            else:
                if not len(vals):
                    raise ValueError(f"column {self.name}: empty feature for scalar")
                self.values.append(vals[0])
        elif self.layout == "ragged":
            self.lengths.append(len(vals))
            if self.kind == proto.BYTES_LIST:
                self.blobs.extend(vals)
            else:
                self.values.extend(vals)
        else:
            raise ValueError(
                f"column {self.name}: got a flat feature for array-of-array type"
            )

    def append_feature_list(self, flist: proto.FeatureList) -> None:
        if self.layout != "ragged2":
            # A FeatureList can also serve ArrayType(scalar): one scalar per
            # inner feature (TFRecordDeserializer.scala:129-143).
            if self.layout == "ragged":
                self.mask.append(True)
                self.lengths.append(len(flist.feature))
                for f in flist.feature:
                    if f.kind != self.kind:
                        raise ValueError(
                            f"column {self.name}: featurelist kind mismatch"
                        )
                    if self.kind == proto.BYTES_LIST:
                        self.blobs.append(f.values[0] if len(f.values) else b"")
                    else:
                        if not len(f.values):
                            raise ValueError(
                                f"column {self.name}: empty inner feature"
                            )
                        self.values.append(f.values[0])
                return
            raise ValueError(f"column {self.name}: FeatureList for scalar type")
        self.mask.append(True)
        self.lengths.append(len(flist.feature))
        for f in flist.feature:
            if f.kind != self.kind:
                raise ValueError(f"column {self.name}: featurelist kind mismatch")
            self.inner_lengths.append(len(f.values))
            if self.kind == proto.BYTES_LIST:
                self.blobs.extend(f.values)
            else:
                self.values.extend(f.values)

    # -- finalize -------------------------------------------------------------

    def _values_array(self) -> np.ndarray:
        if self.kind == proto.INT64_LIST:
            arr = np.asarray(self.values, dtype=np.int64)
            if self.np_dtype != np.int64:
                # IntegerType: two's-complement truncation (Scala Long.toInt)
                arr = arr.astype(self.np_dtype)
            return arr
        return np.asarray(self.values, dtype=self.np_dtype)

    def build(self, num_rows: int) -> Column:
        mask = np.asarray(self.mask, dtype=bool)
        col = Column(self.name, self.dtype, mask=mask)
        if self.layout == "scalar":
            if self.kind == proto.BYTES_LIST:
                col.set_blobs(self.blobs)
            else:
                col.values = self._values_array()
        elif self.layout == "ragged":
            col.offsets = np.concatenate(
                ([0], np.cumsum(np.asarray(self.lengths, dtype=np.int64)))
            )
            if self.kind == proto.BYTES_LIST:
                col.set_blobs(self.blobs)
            else:
                col.values = self._values_array()
        else:
            col.offsets = np.concatenate(
                ([0], np.cumsum(np.asarray(self.lengths, dtype=np.int64)))
            )
            col.inner_offsets = np.concatenate(
                ([0], np.cumsum(np.asarray(self.inner_lengths, dtype=np.int64)))
            )
            if self.kind == proto.BYTES_LIST:
                col.set_blobs(self.blobs)
            else:
                col.values = self._values_array()
        return col


class ColumnarDecoder:
    """Decode batches of serialized records into a ColumnarBatch.

    The schema plays the role of requiredSchema: features not in the schema
    are skipped cheaply; schema fields missing from a record follow the null
    rules (None-able -> masked out, non-nullable -> raise).
    """

    def __init__(self, schema: StructType, record_type: RecordType = RecordType.EXAMPLE):
        self.schema = schema
        self.record_type = RecordType.parse(record_type)
        if self.record_type == RecordType.BYTE_ARRAY and list(schema.names) != ["byteArray"]:
            raise ValueError("ByteArray record type requires the single-column schema")
        # validate eagerly (constructor-time errors like the serializer)
        for f in schema:
            _FieldAcc(f.name, f.data_type, f.nullable)

    def decode_batch(self, records: Sequence[bytes]) -> ColumnarBatch:
        accs = {
            f.name: _FieldAcc(f.name, f.data_type, f.nullable) for f in self.schema
        }
        n = 0
        if self.record_type == RecordType.BYTE_ARRAY:
            acc = accs["byteArray"]
            for rec in records:
                acc.mask.append(True)
                acc.blobs.append(bytes(rec))
                n += 1
        elif self.record_type == RecordType.EXAMPLE:
            for rec in records:
                ex = proto.parse_example(rec)
                for name, acc in accs.items():
                    feat = ex.features.get(name)
                    if feat is None:
                        acc.append_missing()
                    else:
                        acc.append_feature(feat)
                n += 1
        else:
            for rec in records:
                se = proto.parse_sequence_example(rec)
                for name, acc in accs.items():
                    feat = se.context.get(name)
                    if feat is not None:
                        acc.append_feature(feat)
                        continue
                    flist = se.feature_lists.get(name)
                    if flist is not None:
                        acc.append_feature_list(flist)
                    else:
                        acc.append_missing()
                n += 1
        return ColumnarBatch({name: acc.build(n) for name, acc in accs.items()}, n)


# ---------------------------------------------------------------------------
# Ragged -> dense padding (host-side, numpy)
# ---------------------------------------------------------------------------


def batch_to_rows(batch: ColumnarBatch, schema: StructType) -> List[list]:
    """Materialize serde-compatible rows from a columnar batch (the slow,
    row-oriented view — tests, partitioned writes, small exports)."""
    import decimal as _decimal

    def scalar_of(dt: DataType, v):
        if isinstance(dt, DecimalType):
            return _decimal.Decimal(str(v))
        if isinstance(dt, (FloatType, DoubleType)):
            return float(v)
        return int(v)

    n = batch.num_rows
    rows: List[list] = [[None] * len(schema) for _ in range(n)]
    for idx, f in enumerate(schema):
        col = batch[f.name]
        dt = f.data_type
        mask = col.mask
        if isinstance(dt, ArrayType) and isinstance(dt.element_type, ArrayType):
            inner_dt = dt.element_type.element_type
            blobs = col.blobs
            for r in range(n):
                if mask is not None and not mask[r]:
                    continue
                outer = []
                for j in range(col.offsets[r], col.offsets[r + 1]):
                    v0, v1 = int(col.inner_offsets[j]), int(col.inner_offsets[j + 1])
                    if blobs is not None:
                        items = blobs[v0:v1]
                        outer.append(
                            [b.decode("utf-8") for b in items]
                            if isinstance(inner_dt, StringType)
                            else list(items)
                        )
                    else:
                        outer.append([scalar_of(inner_dt, v) for v in col.values[v0:v1]])
                rows[r][idx] = outer
        elif isinstance(dt, ArrayType):
            elem = dt.element_type
            blobs = col.blobs if col.blob is not None else None
            for r in range(n):
                if mask is not None and not mask[r]:
                    continue
                v0, v1 = int(col.offsets[r]), int(col.offsets[r + 1])
                if blobs is not None:
                    items = blobs[v0:v1]
                    rows[r][idx] = (
                        [b.decode("utf-8") for b in items]
                        if isinstance(elem, StringType)
                        else list(items)
                    )
                else:
                    rows[r][idx] = [scalar_of(elem, v) for v in col.values[v0:v1]]
        elif isinstance(dt, (StringType, BinaryType)):
            blobs = col.blobs
            for r in range(n):
                if mask is not None and not mask[r]:
                    continue
                rows[r][idx] = (
                    blobs[r].decode("utf-8") if isinstance(dt, StringType) else blobs[r]
                )
        else:
            vals = col.values
            for r in range(n):
                if mask is not None and not mask[r]:
                    continue
                rows[r][idx] = scalar_of(dt, vals[r])
    return rows


def _slice_blob(col: Column, new: Column, v0: int, v1: int) -> None:
    bo = col.blob_offsets
    b0, b1 = int(bo[v0]), int(bo[v1])
    new.blob = col.blob[b0:b1]
    new.blob_offsets = bo[v0 : v1 + 1] - b0


def slice_batch(batch: ColumnarBatch, start: int, stop: int) -> ColumnarBatch:
    """Row-range view (copy) of a batch — used to cut fixed-size training
    batches out of larger decode chunks."""
    start = max(0, start)
    stop = min(batch.num_rows, stop)
    out: Dict[str, Column] = {}
    for name, col in batch.columns.items():
        new = Column(
            name,
            col.dtype,
            mask=col.mask[start:stop] if col.mask is not None else None,
            hash_buckets=col.hash_buckets,
        )
        if col.inner_offsets is not None:  # ragged2
            o0, o1 = int(col.offsets[start]), int(col.offsets[stop])
            inner = col.inner_offsets[o0 : o1 + 1]
            v0, v1 = int(inner[0]), int(inner[-1])
            new.offsets = col.offsets[start : stop + 1] - o0
            new.inner_offsets = inner - v0
            if col.values is not None:
                new.values = col.values[v0:v1]
            if col.blob is not None:
                _slice_blob(col, new, v0, v1)
        elif col.offsets is not None:  # ragged
            v0, v1 = int(col.offsets[start]), int(col.offsets[stop])
            new.offsets = col.offsets[start : stop + 1] - v0
            if col.values is not None:
                new.values = col.values[v0:v1]
            if col.blob is not None:
                _slice_blob(col, new, v0, v1)
        else:  # scalar
            if col.values is not None:
                new.values = col.values[start:stop]
            if col.blob is not None:
                _slice_blob(col, new, start, stop)
        out[name] = new
    return ColumnarBatch(out, stop - start)


def concat_batches(batches: Sequence[ColumnarBatch]) -> ColumnarBatch:
    """Concatenate batches row-wise (all must share the same columns)."""
    if len(batches) == 1:
        return batches[0]
    first = batches[0]
    out: Dict[str, Column] = {}
    for name, col0 in first.columns.items():
        cols = [b.columns[name] for b in batches]
        new = Column(name, col0.dtype, hash_buckets=col0.hash_buckets)
        if col0.mask is not None:
            new.mask = np.concatenate([c.mask for c in cols])
        if col0.inner_offsets is not None:
            new.offsets = _concat_offsets([np.asarray(c.offsets) for c in cols])
            new.inner_offsets = _concat_offsets(
                [np.asarray(c.inner_offsets) for c in cols]
            )
        elif col0.offsets is not None:
            new.offsets = _concat_offsets([np.asarray(c.offsets) for c in cols])
        if col0.values is not None:
            new.values = np.concatenate([c.values for c in cols])
        if col0.blob is not None:
            new.blob = b"".join(c.blob for c in cols)
            new.blob_offsets = _concat_offsets(
                [np.asarray(c.blob_offsets) for c in cols]
            )
        out[name] = new
    return ColumnarBatch(out, sum(b.num_rows for b in batches))


def _span_gather(offsets: np.ndarray, idx: np.ndarray):
    """Vectorized variable-span gather plan: for span ids ``idx`` over an
    ``offsets`` array, return (flat_element_indices, new_offsets) such that
    elements[flat] laid out contiguously realize spans idx[0], idx[1], ...
    with boundaries new_offsets."""
    offsets = np.asarray(offsets)
    lengths = np.diff(offsets)[idx]
    new_offsets = np.empty(len(idx) + 1, dtype=np.int64)
    new_offsets[0] = 0
    np.cumsum(lengths, out=new_offsets[1:])
    total = int(new_offsets[-1])
    starts = offsets[idx]
    # element j of output = starts[span(j)] + (j - new_offsets[span(j)])
    flat = (
        np.repeat(starts, lengths)
        + np.arange(total, dtype=np.int64)
        - np.repeat(new_offsets[:-1], lengths)
    )
    return flat, new_offsets


def _gather_blob(col: Column, new: Column, value_idx: np.ndarray) -> None:
    """Rebuild blob/blob_offsets for values at ``value_idx`` (in order)."""
    bflat, new_bo = _span_gather(col.blob_offsets, value_idx)
    blob_arr = np.frombuffer(col.blob, dtype=np.uint8)
    new.blob = blob_arr[bflat].tobytes()
    new.blob_offsets = new_bo


def take_rows(batch: ColumnarBatch, indices) -> ColumnarBatch:
    """Row gather: a new batch whose row i is ``batch`` row ``indices[i]``.

    The in-memory shuffle primitive (windowed row shuffle, subsampling,
    sorting): one vectorized pass per column, every layout — scalar, ragged,
    ragged^2, bytes-like, hash-fused, group matrices — handled with the
    same span-gather plan. Oracle-pinned against per-row slice+concat in
    tests/test_columnar.py."""
    raw = np.asarray(indices)
    if raw.dtype == np.bool_:
        # a validity mask would silently cast to 1/0 gather indices —
        # demand explicit positions (np.nonzero(mask)[0] for a mask-select)
        raise TypeError(
            "take_rows takes integer row positions, not a boolean mask; "
            "use np.nonzero(mask)[0]"
        )
    idx = raw.astype(np.int64, copy=False)
    if idx.ndim != 1:
        raise ValueError(f"take_rows expects 1-D indices, got shape {idx.shape}")
    if idx.size and (idx.min() < 0 or idx.max() >= batch.num_rows):
        raise IndexError(
            f"take_rows indices out of range for {batch.num_rows} rows"
        )
    out: Dict[str, Column] = {}
    for name, col in batch.columns.items():
        new = Column(
            name,
            col.dtype,
            mask=col.mask[idx] if col.mask is not None else None,
            hash_buckets=col.hash_buckets,
        )
        if col.inner_offsets is not None:  # ragged2: rows -> inner lists -> values
            inner_idx, new_off = _span_gather(col.offsets, idx)
            vflat, new_inner = _span_gather(col.inner_offsets, inner_idx)
            new.offsets = new_off
            new.inner_offsets = new_inner
            if col.values is not None:
                new.values = np.asarray(col.values)[vflat]
            if col.blob is not None:
                _gather_blob(col, new, vflat)
        elif col.offsets is not None:  # ragged: rows -> values
            vflat, new_off = _span_gather(col.offsets, idx)
            new.offsets = new_off
            if col.values is not None:
                new.values = np.asarray(col.values)[vflat]
            if col.blob is not None:
                _gather_blob(col, new, vflat)
        else:  # scalar (1-D values, or a [N, K] group matrix)
            if col.values is not None:
                new.values = np.asarray(col.values)[idx]
            if col.blob is not None:
                _gather_blob(col, new, idx)
        out[name] = new
    return ColumnarBatch(out, len(idx))


def _concat_offsets(offset_arrays: List[np.ndarray]) -> np.ndarray:
    total = sum(len(o) - 1 for o in offset_arrays)
    out = np.empty(total + 1, dtype=np.int64)
    out[0] = 0
    pos = 0
    base = 0
    for o in offset_arrays:
        n = len(o) - 1
        out[pos + 1 : pos + 1 + n] = o[1:] + base
        base += int(o[-1])
        pos += n
    return out


def pad_ragged(
    values: np.ndarray,
    offsets: np.ndarray,
    max_len: Optional[int] = None,
    pad_value: Union[int, float] = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Ragged [total] + offsets [N+1] -> dense [N, max_len] + lengths [N].

    Rows longer than max_len are truncated; shorter rows are padded with
    ``pad_value``. Vectorized (no per-row Python loop).
    """
    lengths = np.diff(offsets)
    n = len(lengths)
    if max_len is None:
        max_len = int(lengths.max()) if n else 0
    clipped = np.minimum(lengths, max_len)
    dense = np.full((n, max_len), pad_value, dtype=values.dtype if values is not None else np.int64)
    if n and max_len:
        # gather indices: for row i, positions offsets[i] .. offsets[i]+clipped[i]
        col_idx = np.arange(max_len)[None, :]
        valid = col_idx < clipped[:, None]
        src = offsets[:-1][:, None] + col_idx
        dense[valid] = values[src[valid]]
    return dense, clipped.astype(np.int32)


def pad_ragged2(
    values: np.ndarray,
    inner_offsets: np.ndarray,
    row_splits: np.ndarray,
    max_outer: Optional[int] = None,
    max_inner: Optional[int] = None,
    pad_value: Union[int, float] = 0,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Two-level ragged -> dense [N, max_outer, max_inner] + outer lengths
    [N] + inner lengths [N, max_outer]."""
    row_splits = np.asarray(row_splits)
    inner_offsets = np.asarray(inner_offsets)
    outer_lengths = np.diff(row_splits)
    n = len(outer_lengths)
    if max_outer is None:
        max_outer = int(outer_lengths.max()) if n else 0
    inner_lengths_flat = np.diff(inner_offsets)
    if max_inner is None:
        max_inner = int(inner_lengths_flat.max()) if len(inner_lengths_flat) else 0
    dense = np.full((n, max_outer, max_inner), pad_value, dtype=values.dtype)
    inner_len_out = np.zeros((n, max_outer), dtype=np.int32)
    clipped_outer = np.minimum(outer_lengths, max_outer).astype(np.int32)
    if n and max_outer and max_inner:
        # Fully vectorized two-level pad (no per-row Python loop — that costs
        # ~75 ms/batch at the long-doc bench shape): select the kept inner
        # lists row-major with their destination (row, slot), then apply the
        # one-level pad gather over just those lists and scatter into the
        # flattened [n * max_outer, max_inner] dense view.
        slot = np.arange(max_outer)
        keep = slot[None, :] < clipped_outer[:, None]          # [n, max_outer]
        flat_lists = (row_splits[:-1, None] + slot[None, :])[keep]
        dest = (np.arange(n)[:, None] * max_outer + slot[None, :])[keep]
        starts = inner_offsets[flat_lists]
        clipped_inner = np.minimum(
            inner_lengths_flat[flat_lists], max_inner
        ).astype(np.int32)
        col_idx = np.arange(max_inner)[None, :]
        valid = col_idx < clipped_inner[:, None]               # [kept, max_inner]
        dense2 = dense.reshape(n * max_outer, max_inner)
        sub = np.full((len(flat_lists), max_inner), pad_value, dtype=values.dtype)
        sub[valid] = values[(starts[:, None] + col_idx)[valid]]
        dense2[dest] = sub
        inner_len_out.reshape(-1)[dest] = clipped_inner
    return dense, clipped_outer, inner_len_out


def bucket_boundaries(lengths: Sequence[int], num_buckets: int = 4) -> List[int]:
    """Quantile-based bucket boundaries for length-bucketing ragged batches."""
    if not len(lengths):
        return []
    qs = np.quantile(np.asarray(lengths), np.linspace(0, 1, num_buckets + 1)[1:])
    out: List[int] = []
    for q in qs:
        v = int(np.ceil(q))
        if not out or v > out[-1]:
            out.append(v)
    return out
