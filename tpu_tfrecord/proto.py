"""Hand-rolled protobuf wire codec for tf.Example / tf.SequenceExample.

Re-implements natively what the reference pulls in as shaded JVM protobuf
classes (``org.tensorflow:proto`` — ``Example``, ``SequenceExample``,
``Features``, ``Feature``, ``FeatureList(s)``, ``Int64List``, ``FloatList``,
``BytesList``; see reference pom.xml:119-158 and SURVEY.md §2.9). No
TensorFlow or protobuf-runtime dependency: the messages involved are small and
closed, so we speak the proto3 wire format directly.

Message/field numbers (tensorflow/core/example/{example,feature}.proto):

    Example          { Features features = 1; }
    SequenceExample  { Features context = 1; FeatureLists feature_lists = 2; }
    Features         { map<string, Feature> feature = 1; }
    FeatureLists     { map<string, FeatureList> feature_list = 1; }
    FeatureList      { repeated Feature feature = 1; }
    Feature          { oneof kind { BytesList bytes_list = 1;
                                    FloatList float_list = 2;
                                    Int64List int64_list = 3; } }
    BytesList        { repeated bytes value = 1; }
    FloatList        { repeated float value = 1 [packed = true]; }
    Int64List        { repeated int64 value = 1 [packed = true]; }

The Python classes here are deliberately plain (lists/dicts) — the hot decode
path for TPU ingestion bypasses them entirely and goes straight to columnar
numpy buffers (see tpu_tfrecord.columnar and the C++ extension).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

# Feature kind tags, aligned with the proto field numbers so that code
# mirroring the reference's `getKindCase.getNumber` checks reads naturally
# (ref TFRecordDeserializer.scala:179,192,205,216).
BYTES_LIST = 1
FLOAT_LIST = 2
INT64_LIST = 3

_KIND_NAMES = {BYTES_LIST: "bytes_list", FLOAT_LIST: "float_list", INT64_LIST: "int64_list"}


class ProtoDecodeError(ValueError):
    """Raised on malformed protobuf bytes."""


# ---------------------------------------------------------------------------
# Message classes
# ---------------------------------------------------------------------------


@dataclass
class Feature:
    """One feature: a kind (BYTES_LIST/FLOAT_LIST/INT64_LIST or None) + values.

    ``values`` is a list of bytes for BYTES_LIST, a list/array of float for
    FLOAT_LIST, and a list/array of int for INT64_LIST. kind=None mirrors a
    proto Feature with the oneof unset.
    """

    kind: Optional[int] = None
    values: Union[List[bytes], np.ndarray, List[int], List[float]] = field(default_factory=list)

    @staticmethod
    def int64_list(values: Sequence[int]) -> "Feature":
        return Feature(INT64_LIST, [int(v) for v in values])

    @staticmethod
    def float_list(values: Sequence[float]) -> "Feature":
        # float32 round-trip semantics: values are stored as f32 on the wire.
        return Feature(FLOAT_LIST, [float(np.float32(v)) for v in values])

    @staticmethod
    def bytes_list(values: Sequence[bytes]) -> "Feature":
        return Feature(BYTES_LIST, [bytes(v) for v in values])

    @property
    def kind_name(self) -> Optional[str]:
        return _KIND_NAMES.get(self.kind)

    def __len__(self) -> int:
        return len(self.values)


@dataclass
class FeatureList:
    feature: List[Feature] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.feature)


@dataclass
class Example:
    features: Dict[str, Feature] = field(default_factory=dict)

    def serialize(self) -> bytes:
        return encode_example(self)

    @staticmethod
    def parse(data: bytes) -> "Example":
        return parse_example(data)


@dataclass
class SequenceExample:
    context: Dict[str, Feature] = field(default_factory=dict)
    feature_lists: Dict[str, FeatureList] = field(default_factory=dict)

    def serialize(self) -> bytes:
        return encode_sequence_example(self)

    @staticmethod
    def parse(data: bytes) -> "SequenceExample":
        return parse_sequence_example(data)


# ---------------------------------------------------------------------------
# Wire-format primitives
# ---------------------------------------------------------------------------

_WT_VARINT = 0
_WT_I64 = 1
_WT_LEN = 2
_WT_I32 = 5


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        try:
            b = buf[pos]
        except IndexError:
            raise ProtoDecodeError("truncated varint") from None
        result |= (b & 0x7F) << shift
        pos += 1
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ProtoDecodeError("varint too long")


def _zigzag_i64(value: int) -> int:
    """Two's-complement int64 -> unsigned varint value (plain, not zigzag)."""
    return value & 0xFFFFFFFFFFFFFFFF


def _unsigned_to_i64(value: int) -> int:
    return value - (1 << 64) if value >= (1 << 63) else value


def _tag(field_number: int, wire_type: int) -> int:
    return (field_number << 3) | wire_type


def _write_len_field(out: bytearray, field_number: int, payload: bytes) -> None:
    _write_varint(out, _tag(field_number, _WT_LEN))
    _write_varint(out, len(payload))
    out += payload


def _skip_field(buf, pos: int, wire_type: int) -> int:
    if wire_type == _WT_VARINT:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire_type == _WT_I64:
        return pos + 8
    if wire_type == _WT_LEN:
        length, pos = _read_varint(buf, pos)
        return pos + length
    if wire_type == _WT_I32:
        return pos + 4
    raise ProtoDecodeError(f"unsupported wire type {wire_type}")


def _iter_fields(buf, start: int, end: int) -> Iterator[Tuple[int, int, int, int]]:
    """Yield (field_number, wire_type, value_start, value_end) over a range.

    For VARINT fields value_end is the position after the varint and
    value_start its beginning; for LEN fields the (start, end) of the payload.
    """
    pos = start
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field_number = tag >> 3
        wire_type = tag & 0x7
        if wire_type == _WT_LEN:
            length, pos = _read_varint(buf, pos)
            if pos + length > end:
                raise ProtoDecodeError("truncated length-delimited field")
            yield field_number, wire_type, pos, pos + length
            pos += length
        elif wire_type == _WT_VARINT:
            vstart = pos
            _, pos = _read_varint(buf, pos)
            yield field_number, wire_type, vstart, pos
        elif wire_type == _WT_I64:
            if pos + 8 > end:
                raise ProtoDecodeError("truncated fixed64 field")
            yield field_number, wire_type, pos, pos + 8
            pos += 8
        elif wire_type == _WT_I32:
            if pos + 4 > end:
                raise ProtoDecodeError("truncated fixed32 field")
            yield field_number, wire_type, pos, pos + 4
            pos += 4
        else:
            raise ProtoDecodeError(f"unsupported wire type {wire_type}")


# ---------------------------------------------------------------------------
# Encoding
# ---------------------------------------------------------------------------


def _encode_feature(feature: Feature) -> bytes:
    out = bytearray()
    if feature.kind == INT64_LIST:
        payload = bytearray()
        for v in feature.values:
            _write_varint(payload, _zigzag_i64(int(v)))
        inner = bytearray()
        if payload:
            _write_len_field(inner, 1, bytes(payload))
        _write_len_field(out, INT64_LIST, bytes(inner))
    elif feature.kind == FLOAT_LIST:
        values = np.asarray(feature.values, dtype="<f4")
        inner = bytearray()
        if values.size:
            _write_len_field(inner, 1, values.tobytes())
        _write_len_field(out, FLOAT_LIST, bytes(inner))
    elif feature.kind == BYTES_LIST:
        inner = bytearray()
        for v in feature.values:
            _write_len_field(inner, 1, bytes(v))
        _write_len_field(out, BYTES_LIST, bytes(inner))
    elif feature.kind is None:
        pass
    else:
        raise ValueError(f"unknown feature kind {feature.kind}")
    return bytes(out)


def _encode_features_map(features: Dict[str, Feature], field_number: int = 1) -> bytes:
    """Encode a map<string, Feature> — one map-entry submessage per key.

    Keys are emitted in sorted order for deterministic output (protobuf leaves
    map order unspecified; the reference inherits JVM HashMap order).
    """
    out = bytearray()
    for name in sorted(features):
        entry = bytearray()
        key_bytes = name.encode("utf-8")
        _write_len_field(entry, 1, key_bytes)
        _write_len_field(entry, 2, _encode_feature(features[name]))
        _write_len_field(out, field_number, bytes(entry))
    return bytes(out)


def _encode_feature_list(flist: FeatureList) -> bytes:
    out = bytearray()
    for feature in flist.feature:
        _write_len_field(out, 1, _encode_feature(feature))
    return bytes(out)


def encode_example(example: Example) -> bytes:
    out = bytearray()
    _write_len_field(out, 1, _encode_features_map(example.features))
    return bytes(out)


def encode_sequence_example(se: SequenceExample) -> bytes:
    out = bytearray()
    _write_len_field(out, 1, _encode_features_map(se.context))
    fl_out = bytearray()
    for name in sorted(se.feature_lists):
        entry = bytearray()
        _write_len_field(entry, 1, name.encode("utf-8"))
        _write_len_field(entry, 2, _encode_feature_list(se.feature_lists[name]))
        _write_len_field(fl_out, 1, bytes(entry))
    _write_len_field(out, 2, bytes(fl_out))
    return bytes(out)


# ---------------------------------------------------------------------------
# Decoding
# ---------------------------------------------------------------------------


def _parse_feature(buf, start: int, end: int) -> Feature:
    # Repeated encounters of the same list field MERGE (protobuf submessage
    # merge semantics): values concatenate. A different oneof kind replaces.
    kind: Optional[int] = None
    values: Union[List[bytes], List[int], List[float]] = []
    for fnum, wtype, vstart, vend in _iter_fields(buf, start, end):
        if fnum == BYTES_LIST and wtype == _WT_LEN:
            if kind != BYTES_LIST:
                kind, values = BYTES_LIST, []
            for inum, iwt, istart, iend in _iter_fields(buf, vstart, vend):
                if inum == 1 and iwt == _WT_LEN:
                    values.append(bytes(buf[istart:iend]))
        elif fnum == FLOAT_LIST and wtype == _WT_LEN:
            if kind != FLOAT_LIST:
                kind, values = FLOAT_LIST, []
            for inum, iwt, istart, iend in _iter_fields(buf, vstart, vend):
                if inum != 1:
                    continue
                if iwt == _WT_LEN:  # packed
                    if (iend - istart) % 4:
                        raise ProtoDecodeError("packed float payload not 4-aligned")
                    values.extend(
                        np.frombuffer(buf, dtype="<f4", count=(iend - istart) // 4, offset=istart).tolist()
                    )
                elif iwt == _WT_I32:  # unpacked
                    values.append(struct.unpack_from("<f", buf, istart)[0])
        elif fnum == INT64_LIST and wtype == _WT_LEN:
            if kind != INT64_LIST:
                kind, values = INT64_LIST, []
            for inum, iwt, istart, iend in _iter_fields(buf, vstart, vend):
                if inum != 1:
                    continue
                if iwt == _WT_LEN:  # packed
                    pos = istart
                    while pos < iend:
                        raw, pos = _read_varint(buf, pos)
                        if pos > iend:
                            # a varint crossing the declared payload end is
                            # malformed — reading on into whatever bytes
                            # follow would silently fabricate a value
                            raise ProtoDecodeError(
                                "truncated varint in packed int64 list"
                            )
                        values.append(_unsigned_to_i64(raw))
                elif iwt == _WT_VARINT:  # unpacked
                    raw, _ = _read_varint(buf, istart)
                    values.append(_unsigned_to_i64(raw))
    return Feature(kind, values)


def _parse_features_map(buf, start: int, end: int) -> Dict[str, Feature]:
    result: Dict[str, Feature] = {}
    for fnum, wtype, vstart, vend in _iter_fields(buf, start, end):
        if fnum != 1 or wtype != _WT_LEN:
            continue
        name = None
        feature = Feature()
        for enum_, ewt, estart, eend in _iter_fields(buf, vstart, vend):
            if enum_ == 1 and ewt == _WT_LEN:
                name = bytes(buf[estart:eend]).decode("utf-8")
            elif enum_ == 2 and ewt == _WT_LEN:
                feature = _parse_feature(buf, estart, eend)
        if name is not None:
            result[name] = feature
    return result


def _parse_feature_list(buf, start: int, end: int) -> FeatureList:
    flist = FeatureList()
    for fnum, wtype, vstart, vend in _iter_fields(buf, start, end):
        if fnum == 1 and wtype == _WT_LEN:
            flist.feature.append(_parse_feature(buf, vstart, vend))
    return flist


def parse_example(data: bytes) -> Example:
    example = Example()
    for fnum, wtype, vstart, vend in _iter_fields(data, 0, len(data)):
        if fnum == 1 and wtype == _WT_LEN:
            example.features.update(_parse_features_map(data, vstart, vend))
    return example


def parse_sequence_example(data: bytes) -> SequenceExample:
    se = SequenceExample()
    for fnum, wtype, vstart, vend in _iter_fields(data, 0, len(data)):
        if fnum == 1 and wtype == _WT_LEN:
            se.context.update(_parse_features_map(data, vstart, vend))
        elif fnum == 2 and wtype == _WT_LEN:
            for gnum, gwt, gstart, gend in _iter_fields(data, vstart, vend):
                if gnum != 1 or gwt != _WT_LEN:
                    continue
                name = None
                flist = FeatureList()
                for enum_, ewt, estart, eend in _iter_fields(data, gstart, gend):
                    if enum_ == 1 and ewt == _WT_LEN:
                        name = bytes(data[estart:eend]).decode("utf-8")
                    elif enum_ == 2 and ewt == _WT_LEN:
                        flist = _parse_feature_list(data, estart, eend)
                if name is not None:
                    se.feature_lists[name] = flist
    return se
