"""Real-network remote tier: a stdlib HTTP(S) filesystem client and a
fault-injecting Range server.

Every remote-throughput and remote-fault claim used to ride on fsspec
``memory://`` plus injected RTT — wrapped file objects, never a socket
(ROADMAP #3, VERDICT "missing" #1). This module closes that gap with two
halves that meet over a REAL TCP connection:

- ``HttpFS``: a read-only filesystem for ``http://``/``https://`` URLs
  built on ``http.client`` only (no fsspec, no aiohttp). Reads are Range
  requests; every ``open()`` is its own connection (genuinely independent
  handles, so ``PrefetchReader`` pipelines block fetches like real
  object-store GETs). The client VERIFIES ``Content-Range`` against the
  offset it asked for — a lying server is a loud ``BadContentRangeError``
  (counted in ``remote.bad_range``), never silently shifted bytes — and a
  body that ends before its declared ``Content-Length`` raises (so the
  block-fetch retry resumes from the exact byte offset instead of
  trusting a truncated read as EOF).

- ``serve_directory`` / ``FaultingRangeServer``: a threaded stdlib HTTP
  server over a local directory — the test/bench backend. Range support,
  one thread per connection, and (when given a FaultPlan) seeded faults
  that fire at the SERVER side of the socket: connection RST mid-body,
  truncated bodies, 503/429 with ``Retry-After``, slow-trickle stalls,
  and wrong ``Content-Range`` headers. Every fired fault lands in the
  same replayable ledger file/service faults use (faults.FaultPlan);
  the plan key for a file GET is ``<url path>@<range start>`` so
  concurrent block fetches get deterministic per-offset ordinals.

Client-side connect faults (connection REFUSED as the client observes
it) come from the chaos seam: ``install_chaos`` points ``_CHAOS_PLAN``
at the active plan and every connection establishment consults it with
``op="connect"`` against the peer ``host:port``.

This is deliberately read-only: the write path keeps committing through
rename-capable stores; HTTP is an ingest tier.
"""

from __future__ import annotations

import email.utils
import http.client
import json
import os
import posixpath
import re
import socket
import struct
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import BinaryIO, Iterator, List, Optional, Tuple

from tpu_tfrecord.metrics import METRICS

#: Chaos seam (tpu_tfrecord.faults.install_chaos): while a plan is
#: installed, every client connection establishment consults it with
#: ``op="connect"`` against the peer "host:port" — a transient/permanent
#: error rule there IS connection-refused as the client observes it.
_CHAOS_PLAN = None

#: Content type the fault server stamps on directory-index responses;
#: HttpFS uses it to tell files from directories without a convention
#: like trailing slashes.
DIR_CONTENT_TYPE = "application/vnd.tpu-tfrecord.dirindex+json"

_REDIRECT_STATUSES = (301, 302, 303, 307, 308)
_MAX_REDIRECTS = 3


class BadContentRangeError(OSError):
    """The server's ``Content-Range`` start disagrees with the offset the
    client requested: a LYING server. Raised before a single byte of the
    mislabeled body is surfaced — wrong data must be a loud error, never
    records decoded from shifted bytes."""


class HTTPStatusError(OSError):
    """A non-success HTTP response (503/429/...). Carries ``status`` and
    the parsed ``retry_after`` seconds (None when absent) so retry loops
    can honor the server's own pacing hint."""

    def __init__(self, msg: str, status: int = 0,
                 retry_after: Optional[float] = None):
        super().__init__(msg)
        self.status = status
        self.retry_after = retry_after


def _connect_timeout() -> Optional[float]:
    raw = os.environ.get("TFR_HTTP_TIMEOUT_S", "").strip()
    return float(raw) if raw else None


def _parse_retry_after(value: Optional[str]) -> Optional[float]:
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:  # HTTP-date form
        when = email.utils.parsedate_to_datetime(value)
        import datetime

        now = datetime.datetime.now(datetime.timezone.utc)
        return max(0.0, (when - now).total_seconds())
    except (TypeError, ValueError):
        return None


def _split_url(url: str) -> Tuple[str, str, int, str]:
    """(scheme, host, port, path+query) — path defaults to '/'."""
    u = urllib.parse.urlsplit(url)
    if u.scheme not in ("http", "https"):
        raise ValueError(f"not an http(s) URL: {url!r}")
    if not u.hostname:
        raise ValueError(f"http(s) URL without a host: {url!r}")
    port = u.port or (443 if u.scheme == "https" else 80)
    path = u.path or "/"
    if u.query:
        path += "?" + u.query
    return u.scheme, u.hostname, port, path


def _new_connection(scheme: str, host: str, port: int):
    """One fresh connection, through the chaos connect seam."""
    plan = _CHAOS_PLAN
    if plan is not None:
        plan.apply_socket("connect", f"{host}:{port}")
    timeout = _connect_timeout()
    kwargs = {} if timeout is None else {"timeout": timeout}
    if scheme == "https":
        return http.client.HTTPSConnection(host, port, **kwargs)
    return http.client.HTTPConnection(host, port, **kwargs)


class _HttpFile:
    """Read-only file object over HTTP Range requests.

    Lazy: ``seek`` just moves the position; the next ``read`` issues ONE
    open-ended range request (``bytes=pos-``) and streams from it, so a
    sequential consumer pays one request per open/seek, not per read.
    The response is validated before any byte is surfaced:

    - 206 must carry a ``Content-Range`` whose start equals the requested
      offset (``BadContentRangeError`` otherwise — the lying-server case);
    - a 200 from a server that ignored the Range header is accepted by
      discarding ``pos`` bytes (correct, slow, counted nowhere — only
      non-range-capable servers hit it);
    - a body that ends before its declared length raises ``OSError``
      ("truncated body"), never reads as EOF.
    """

    def __init__(self, url: str):
        self._url = url
        self._scheme, self._host, self._port, self._path = _split_url(url)
        self._pos = 0
        self._conn = None
        self._resp = None
        self._remaining: Optional[int] = None  # bytes left in this response
        self._size: Optional[int] = None  # total object size when known
        self._closed = False

    # -- request plumbing ----------------------------------------------------

    def _drop_response(self) -> None:
        """Abandon the in-flight response AND its connection: a
        partially-read HTTP/1.1 response poisons the connection for
        reuse. (Fully-drained responses keep the connection alive via
        ``_read_raw``'s remaining==0 path, which clears only ``_resp``.)"""
        self._resp = None
        self._remaining = None
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:  # graftlint: swallow(closing a poisoned keep-alive connection)
                pass
            self._conn = None

    def _start(self) -> None:
        """Issue ``GET`` with ``Range: bytes=pos-`` and validate. Follows
        bounded redirects (CDN offload / signed-URL front ends) — the
        metadata layer (HttpFS._request) already does, and a dataset that
        discovers must also read."""
        for _ in range(_MAX_REDIRECTS + 1):
            if self._start_once():
                return
        raise OSError(f"too many redirects reading {self._url}")

    def _redirect_to(self, location: str) -> None:
        self._drop_response()
        self._url = urllib.parse.urljoin(self._url, location)
        self._scheme, self._host, self._port, self._path = _split_url(
            self._url
        )

    def _start_once(self) -> bool:
        """One request/validate round; False = redirected, go again."""
        if self._conn is None:
            self._conn = _new_connection(self._scheme, self._host, self._port)
        discard = 0
        try:
            self._conn.request(
                "GET", self._path, headers={"Range": f"bytes={self._pos}-"}
            )
            resp = self._conn.getresponse()
            status = resp.status
            if status in _REDIRECT_STATUSES:
                loc = resp.headers.get("Location")
                try:
                    resp.read()
                except Exception:  # graftlint: swallow(malformed Location: loud OSError raised just below)
                    pass
                if not loc:
                    self._drop_response()
                    raise OSError(
                        f"redirect without Location reading {self._url}"
                    )
                self._redirect_to(loc)
                return False
            if status == 206:
                m = re.match(
                    r"bytes (\d+)-(\d+)/(\d+|\*)",
                    resp.headers.get("Content-Range", ""),
                )
                if not m:
                    METRICS.count("remote.bad_range")
                    resp.close()
                    self._drop_response()
                    raise BadContentRangeError(
                        f"206 without a parseable Content-Range from {self._url}"
                    )
                start, end, total = m.group(1), m.group(2), m.group(3)
                if int(start) != self._pos:
                    METRICS.count("remote.bad_range")
                    resp.close()
                    self._drop_response()
                    raise BadContentRangeError(
                        f"server returned range starting at byte {start} for a "
                        f"request at byte {self._pos} on {self._url} — refusing "
                        "to read shifted data"
                    )
                self._remaining = int(end) - int(start) + 1
                if total != "*":
                    self._size = int(total)
            elif status == 200:
                # range ignored: full body; discard up to pos (slow path).
                # remaining counts the WHOLE body — the discard loop below
                # runs it down to size - pos through _read_raw.
                length = resp.headers.get("Content-Length")
                self._remaining = int(length) if length else None
                self._size = int(length) if length else None
                discard = self._pos
            elif status == 416:
                # requested start at/past EOF: clean EOF, not an error
                resp.read()
                self._resp = None
                self._remaining = 0
                return True
            else:
                retry_after = _parse_retry_after(resp.headers.get("Retry-After"))
                try:
                    resp.read()
                except Exception:  # graftlint: swallow(unparseable Retry-After: HTTPStatusError raised without it)
                    pass
                self._drop_response()
                raise HTTPStatusError(
                    f"HTTP {status} reading {self._url}",
                    status=status,
                    retry_after=retry_after,
                )
            self._resp = resp
            while discard > 0:
                chunk = self._read_raw(min(discard, 1 << 20))
                if not chunk:
                    break
                discard -= len(chunk)
            return True
        except (http.client.HTTPException, socket.error) as e:
            self._drop_response()
            if isinstance(e, OSError):
                raise
            raise OSError(f"HTTP request failed on {self._url}: {e}") from e

    def _read_raw(self, n: int) -> bytes:
        """One validated read off the live response."""
        resp = self._resp
        try:
            data = resp.read(n)
        except (http.client.HTTPException, socket.error) as e:
            self._drop_response()
            if isinstance(e, OSError):
                raise
            raise OSError(
                f"connection died mid-body at byte {self._pos} of {self._url}: {e}"
            ) from e
        if self._remaining is not None:
            if not data and self._remaining > 0:
                # the server closed before delivering Content-Length bytes:
                # a TRUNCATED body must raise (retryable, resumable at
                # self._pos), never read as end-of-object
                self._drop_response()
                raise OSError(
                    f"truncated body: connection closed {self._remaining} "
                    f"bytes early at byte {self._pos} of {self._url}"
                )
            self._remaining -= len(data)
            if self._remaining <= 0:
                # fully consumed: the connection is clean for reuse
                self._resp = None
                self._remaining = None
        return data

    # -- file-object surface -------------------------------------------------

    def read(self, size: int = -1) -> bytes:
        if self._closed:
            raise ValueError("read on closed _HttpFile")
        if size is None or size < 0:
            parts = []
            while True:
                chunk = self.read(8 << 20)
                if not chunk:
                    return b"".join(parts)
                parts.append(chunk)
        if size == 0:
            return b""
        if self._size is not None and self._pos >= self._size:
            return b""
        if self._resp is None:
            if self._remaining == 0:  # 416: at/past EOF
                return b""
            self._start()
            if self._resp is None:
                return b""
        data = self._read_raw(size)
        self._pos += len(data)
        return data

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 1:
            pos = self._pos + pos
        elif whence == 2:
            if self._size is None:
                raise OSError("seek from end without a known size")
            pos = self._size + pos
        elif whence != 0:
            raise ValueError(f"unsupported whence {whence}")
        if pos != self._pos:
            if self._resp is not None:
                # mid-body: the partially-read response poisons the
                # connection — drop both
                self._drop_response()
            else:
                # fully drained (or never started): the keep-alive
                # connection is clean, the next read re-ranges on it
                self._remaining = None
        self._pos = pos
        return pos

    def tell(self) -> int:
        return self._pos

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._drop_response()

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "_HttpFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class HttpFS:
    """Read-only stdlib filesystem for ``http://``/``https://`` URLs.

    Matches the surface ``LocalFS``/``FsspecFS`` expose to the read path
    (open/exists/isfile/isdir/listdir/walk_files/size/info/normalize);
    write-side methods raise. Directory listings understand the fault
    server's JSON index (DIR_CONTENT_TYPE) and degrade to parsing the
    ``href``s of a generic autoindex HTML page.

    ``independent_read_handles`` is declared True: every ``open()`` is a
    fresh connection with its own cursor, so PrefetchReader runs block
    fetches concurrently — the whole point of a real-network tier.
    """

    independent_read_handles = True
    protocol = ("http", "https")

    def __init__(self, url: str = "http://"):
        del url  # stateless: every path carries its own authority

    # -- metadata ------------------------------------------------------------

    def _request(self, method: str, url: str, allow_404: bool = False):
        """(status, headers, body bytes | None, final_url) with bounded
        redirects — final_url is where the response actually came from,
        so callers can see e.g. that a bare directory name was redirected
        to its trailing-slash listing."""
        current = url
        for _ in range(_MAX_REDIRECTS + 1):
            scheme, host, port, path = _split_url(current)
            conn = _new_connection(scheme, host, port)
            try:
                conn.request(method, path)
                resp = conn.getresponse()
                if resp.status in _REDIRECT_STATUSES:
                    loc = resp.headers.get("Location")
                    resp.read()
                    if not loc:
                        raise OSError(f"redirect without Location from {current}")
                    current = urllib.parse.urljoin(current, loc)
                    continue
                body = None if method == "HEAD" else resp.read()
                if resp.status == 404:
                    if allow_404:
                        return resp.status, resp.headers, body, current
                    raise FileNotFoundError(f"HTTP 404: {url}")
                if resp.status >= 400:
                    raise HTTPStatusError(
                        f"HTTP {resp.status} on {method} {url}",
                        status=resp.status,
                        retry_after=_parse_retry_after(
                            resp.headers.get("Retry-After")
                        ),
                    )
                return resp.status, resp.headers, body, current
            except (http.client.HTTPException, socket.error) as e:
                if isinstance(e, OSError):
                    raise
                raise OSError(f"HTTP {method} failed on {url}: {e}") from e
            finally:
                conn.close()
        raise OSError(f"too many redirects resolving {url}")

    def normalize(self, path: str) -> str:
        return path

    def open(self, path: str, mode: str) -> BinaryIO:
        if mode not in ("rb", "r"):
            raise OSError(
                f"http(s) filesystem is read-only: cannot open {path!r} "
                f"with mode {mode!r}"
            )
        return _HttpFile(path)

    def exists(self, path: str) -> bool:
        status, _, _, _ = self._request("HEAD", path, allow_404=True)
        return status == 200

    def _head_type(self, path: str) -> Tuple[int, str, bool]:
        """(status, content-type, landed_on_dir_listing) — the last flag
        is True when the (possibly redirected) final URL ends in '/',
        the generic-autoindex directory signal."""
        status, headers, _, final = self._request("HEAD", path,
                                                  allow_404=True)
        ctype = (headers.get("Content-Type") or "").split(";")[0].strip()
        return status, ctype, final.rstrip("?").endswith("/")

    def isfile(self, path: str) -> bool:
        status, ctype, on_dir = self._head_type(path)
        if status != 200 or ctype == DIR_CONTENT_TYPE:
            return False
        # a generic autoindex server 301s 'ds' -> 'ds/' and serves the
        # HTML listing: that is a DIRECTORY, not an html shard — without
        # this, the doctor would scan the listing page as TFRecord bytes
        return not (on_dir and ctype == "text/html")

    def isdir(self, path: str) -> bool:
        status, ctype, on_dir = self._head_type(path)
        if status == 200:
            return ctype == DIR_CONTENT_TYPE or (
                ctype == "text/html" and (on_dir or path.endswith("/"))
            )
        if status == 404 and not path.endswith("/"):
            # generic servers 404 the bare name and serve the listing at
            # path + "/"
            status, ctype, _ = self._head_type(path + "/")
            return status == 200 and ctype in (DIR_CONTENT_TYPE, "text/html")
        return False

    def size(self, path: str) -> int:
        status, headers, _, _ = self._request("HEAD", path)
        length = headers.get("Content-Length")
        if length is None:
            raise OSError(f"no Content-Length for {path}")
        return int(length)

    def info(self, path: str) -> dict:
        """Backend metadata in the key vocabulary ``cache.source_stat``
        scans (size + mtime / ETag): a remote rewrite with the same size
        still invalidates epoch-cache entries."""
        status, headers, _, _ = self._request("HEAD", path)
        out: dict = {"name": path, "type": "file"}
        length = headers.get("Content-Length")
        if length is not None:
            out["size"] = int(length)
        lm = headers.get("Last-Modified")
        if lm:
            try:
                out["mtime"] = email.utils.parsedate_to_datetime(lm).timestamp()
            except (TypeError, ValueError):
                pass
        etag = headers.get("ETag")
        if etag:
            out["ETag"] = etag
        return out

    # -- listing / discovery -------------------------------------------------

    def _entries(self, path: str) -> List[dict]:
        """Directory entries as dicts with name/type and (when the index
        provides it) size. Tries the URL as given, then with a trailing
        slash (generic autoindex servers)."""
        status, headers, body, _ = self._request("GET", path, allow_404=True)
        if status == 404 and not path.endswith("/"):
            status, headers, body, _ = self._request("GET", path + "/",
                                                     allow_404=True)
        if status != 200:
            raise FileNotFoundError(f"HTTP {status} listing {path}")
        ctype = (headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype == DIR_CONTENT_TYPE:
            doc = json.loads(body.decode("utf-8"))
            return list(doc.get("entries", []))
        # generic autoindex HTML: hrefs relative to the directory
        entries = []
        for href in re.findall(rb'href="([^"?#]+)"', body or b""):
            name = urllib.parse.unquote(href.decode("utf-8", "replace"))
            if name.startswith(("/", "../")) or name in (".", "./"):
                continue
            is_dir = name.endswith("/")
            entries.append(
                {"name": name.rstrip("/"), "type": "directory" if is_dir
                 else "file"}
            )
        return entries

    def listdir(self, path: str) -> List[str]:
        return sorted(e["name"] for e in self._entries(path))

    def walk_files(self, root: str, keep) -> Iterator[Tuple[str, int]]:
        """Deterministic (sorted) walk yielding (url, size); directory
        recursion and file order match the other backends so every host
        derives the same global shard order. Sizes come from the JSON
        index when present, one HEAD per file otherwise."""
        stack = [root.rstrip("/")]
        while stack:
            dirurl = stack.pop()
            files, dirs = [], []
            for e in self._entries(dirurl):
                name = str(e.get("name", "")).strip("/")
                if not name or not keep(name):
                    continue
                child = f"{dirurl}/{name}"
                if e.get("type") == "directory":
                    dirs.append(child)
                else:
                    size = e.get("size")
                    if size is None:
                        size = self.size(child)
                    files.append((child, int(size)))
            for furl, size in sorted(files):
                yield furl, size
            stack.extend(sorted(dirs, reverse=True))  # pop() visits in order

    def glob(self, pattern: str) -> List[str]:
        raise OSError(
            f"glob is not supported over http(s) ({pattern!r}): point the "
            "reader at the dataset directory or a concrete file URL"
        )

    # -- write side: loudly read-only ---------------------------------------

    def _read_only(self, op: str, path: str):
        raise OSError(
            f"http(s) filesystem is read-only: {op} on {path!r} is not "
            "supported (HTTP is an ingest tier; write through a "
            "rename-capable store)"
        )

    def makedirs(self, path: str) -> None:
        self._read_only("makedirs", path)

    def remove(self, path: str) -> None:
        self._read_only("remove", path)

    def rmtree(self, path: str, ignore_errors: bool = False) -> None:
        if not ignore_errors:
            self._read_only("rmtree", path)

    def rmdir(self, path: str) -> None:
        self._read_only("rmdir", path)

    def rename(self, src: str, dst: str) -> None:
        self._read_only("rename", src)

    def touch(self, path: str) -> None:
        self._read_only("touch", path)


# ---------------------------------------------------------------------------
# The test/bench backend: a threaded Range server with socket-level faults
# ---------------------------------------------------------------------------


class _RangeHandler(BaseHTTPRequestHandler):
    """One request handler over ``server.root``. HTTP/1.1 with real
    keep-alive, Range support on files, a JSON index for directories, and
    the FaultPlan hook on file GETs (metadata requests are served clean so
    discovery does not eat rule firings meant for reads)."""

    protocol_version = "HTTP/1.1"
    server_version = "TfrRangeHTTP/1.0"

    def log_message(self, fmt, *args):  # noqa: D102 — silence stderr spam
        pass

    # -- path resolution -----------------------------------------------------

    def _resolve(self) -> Optional[str]:
        """Filesystem path for the request URL, or None when it escapes
        the served root (traversal) — answered 404, never served."""
        raw = urllib.parse.unquote(urllib.parse.urlsplit(self.path).path)
        norm = posixpath.normpath(raw)
        if norm.startswith(("..", "/..")):
            return None
        local = os.path.join(self.server.root, norm.lstrip("/"))
        local = os.path.normpath(local)
        root = os.path.normpath(self.server.root)
        if not (local == root or local.startswith(root + os.sep)):
            return None
        return local

    def _parse_range(self, size: int) -> Optional[Tuple[int, int]]:
        """(start, end) inclusive, or None for a whole-object request.
        Raises ValueError for an unsatisfiable start (→ 416)."""
        header = self.headers.get("Range")
        if not header:
            return None
        m = re.match(r"bytes=(\d+)-(\d*)$", header.strip())
        if not m:
            return None  # unsupported form: serve the whole object (200)
        start = int(m.group(1))
        if start >= size:
            raise ValueError("range start past EOF")
        end = int(m.group(2)) if m.group(2) else size - 1
        return start, min(end, size - 1)

    # -- responses -----------------------------------------------------------

    def _send_simple(self, status: int, body: bytes,
                     ctype: str = "text/plain",
                     extra_headers: Optional[dict] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)

    def _send_dir_index(self, local: str) -> None:
        entries = []
        with os.scandir(local) as it:
            for e in sorted(it, key=lambda e: e.name):
                if e.is_dir(follow_symlinks=False):
                    entries.append({"name": e.name, "type": "directory"})
                elif e.is_file(follow_symlinks=True):
                    entries.append(
                        {"name": e.name, "type": "file",
                         "size": e.stat().st_size}
                    )
        body = json.dumps({"entries": entries}).encode("utf-8")
        self._send_simple(200, body, ctype=DIR_CONTENT_TYPE)

    def _rst(self) -> None:
        """Reset the connection: SO_LINGER 0 makes close() send RST, the
        hard mid-transfer death a FIN can't model."""
        try:
            self.connection.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
            )
        except OSError:
            pass
        self.close_connection = True
        try:
            self.connection.close()
        except OSError:
            pass

    # -- the served read -----------------------------------------------------

    def _serve_file(self, local: str, head: bool) -> None:
        try:
            st = os.stat(local)
            size = st.st_size
        except OSError:
            self._send_simple(404, b"not found")
            return
        try:
            rng = self._parse_range(size)
        except ValueError:
            self._send_simple(
                416, b"", extra_headers={"Content-Range": f"bytes */{size}"}
            )
            return
        start, end = rng if rng is not None else (0, size - 1)

        if not head:
            # data fetches only: dir-index and HEAD metadata requests are
            # not the link being paid for shard bytes
            self.server.note_file_get()
        # ---- fault hook: op="http", keyed per (path, offset) ----
        plan = self.server.plan
        fired = []
        if plan is not None and not head:
            urlpath = urllib.parse.unquote(
                urllib.parse.urlsplit(self.path).path
            )
            fired = plan.decide("http", f"{urlpath}@{start}")
        stall_s = 0.0
        trickle = None  # (chunk_bytes, pause_s)
        truncate_at = None  # bytes of body actually sent
        reset_at = None  # RST after this many body bytes
        shift = 0
        for f in fired:
            rule = f["_rule"]
            kind = f["kind"]
            if kind == "stall":
                stall_s += rule.stall_ms / 1000.0
            elif kind == "trickle":
                trickle = (max(1, rule.cap_bytes or 1024),
                           rule.stall_ms / 1000.0)
            elif kind == "http_error":
                if stall_s:
                    plan.sleep(stall_s)
                extra = {}
                if rule.retry_after_s:
                    extra["Retry-After"] = f"{rule.retry_after_s:g}"
                self._send_simple(
                    rule.status, b"injected http_error", extra_headers=extra
                )
                self.close_connection = True
                return
            elif kind in ("transient_error", "permanent_error"):
                if stall_s:
                    plan.sleep(stall_s)
                self._send_simple(500, b"injected server error")
                self.close_connection = True
                return
            elif kind == "truncated_body":
                n = end - start + 1
                truncate_at = min(rule.cap_bytes or max(1, n // 2), n)
            elif kind == "reset":
                n = end - start + 1
                reset_at = min(rule.cap_bytes or max(0, n // 2), n)
            elif kind == "bad_content_range":
                # lie CONSISTENTLY: header and body both from the shifted
                # offset — only the client's Content-Range check stands
                # between this and silently corrupted records
                shift = rule.shift_bytes
        if stall_s:
            plan.sleep(stall_s)
        if self.server.latency_s:
            # simulated per-request link RTT for the bench depth sweep —
            # still a real connection, the handler just answers late
            import time as _time

            _time.sleep(self.server.latency_s)

        if shift:
            start = min(max(0, start + shift), max(0, size - 1))
            end = min(max(start, end + shift), size - 1)
        body_len = end - start + 1
        self.send_response(206 if rng is not None else 200)
        self.send_header("Accept-Ranges", "bytes")
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(body_len))
        # freshness stamps: the epoch cache keys remote invalidation on
        # these (cache.source_stat via HttpFS.info)
        self.send_header(
            "Last-Modified", email.utils.formatdate(st.st_mtime, usegmt=True)
        )
        self.send_header("ETag", f'"{st.st_mtime_ns:x}-{size:x}"')
        if rng is not None:
            self.send_header("Content-Range", f"bytes {start}-{end}/{size}")
        self.end_headers()
        if head:
            return

        to_send = body_len if truncate_at is None else truncate_at
        chunk_bytes = trickle[0] if trickle else (256 << 10)
        sent = 0
        try:
            with open(local, "rb") as fh:
                fh.seek(start)
                while sent < to_send:
                    if reset_at is not None and sent >= reset_at:
                        self._rst()
                        return
                    n = min(chunk_bytes, to_send - sent)
                    if reset_at is not None:
                        # stop EXACTLY at the reset point: the RST must
                        # land mid-body, not after the whole (small)
                        # object already reached the client's buffers
                        n = min(n, reset_at - sent)
                    data = fh.read(n)
                    if not data:
                        break
                    self.wfile.write(data)
                    self.wfile.flush()
                    sent += len(data)
                    if trickle and sent < to_send:
                        plan.sleep(trickle[1])
            if reset_at is not None and sent >= reset_at:
                self._rst()
                return
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True
            return
        if truncate_at is not None and truncate_at < body_len:
            # we declared body_len bytes and sent fewer: drop the
            # connection so the client sees the premature FIN now
            self.close_connection = True
            try:
                self.connection.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def _handle(self, head: bool) -> None:
        self.server.note_request(self.command, self.path)
        raw = urllib.parse.urlsplit(self.path).path
        if raw.startswith("/redirect/"):
            # test route: 302 to the same resource at its real path — the
            # CDN-offload shape both the metadata layer AND the data reads
            # must follow
            self.send_response(302)
            self.send_header("Location", raw[len("/redirect"):])
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        local = self._resolve()
        if local is None or not os.path.exists(local):
            self._send_simple(404, b"not found")
            return
        if os.path.isdir(local):
            self._send_dir_index(local)
            return
        self._serve_file(local, head)

    def do_GET(self) -> None:  # noqa: N802 — http.server API
        self._handle(head=False)

    def do_HEAD(self) -> None:  # noqa: N802
        self._handle(head=True)


class _QuietThreadingHTTPServer(ThreadingHTTPServer):
    """A connection dying mid-request (the client abandoned it, the RST
    fault closed it, a consumer was SIGKILLed) is business as usual for a
    fault-injection backend — not a traceback on stderr."""

    def handle_error(self, request, client_address):
        import sys as _sys

        exc = _sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                            ConnectionAbortedError)):
            return
        super().handle_error(request, client_address)


class FaultingRangeServer:
    """Threaded HTTP server over ``root`` with the FaultPlan hook.

    ``plan`` may be None (clean serving), or a faults.FaultPlan whose
    ``op="http"`` rules fire on file GETs; fired faults land in the
    plan's replayable ledger. ``latency_s`` adds a fixed per-request
    delay — the bench's simulated link RTT on top of real sockets.
    """

    def __init__(self, root: str, plan=None, latency_s: float = 0.0,
                 host: str = "127.0.0.1", port: int = 0):
        self.root = os.path.abspath(root)
        httpd = _QuietThreadingHTTPServer((host, port), _RangeHandler)
        httpd.daemon_threads = True
        httpd.root = self.root
        httpd.plan = plan
        httpd.latency_s = latency_s
        lock = threading.Lock()
        counts = {"requests": 0, "gets": 0, "file_gets": 0}

        def note_request(command: str, path: str) -> None:
            with lock:
                counts["requests"] += 1
                if command == "GET":
                    counts["gets"] += 1

        def note_file_get() -> None:
            with lock:
                counts["file_gets"] += 1

        httpd.note_request = note_request
        httpd.note_file_get = note_file_get
        self._counts = counts
        self._counts_lock = lock
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name="tfr-http-backend",
        )

    def start(self) -> "FaultingRangeServer":
        self._thread.start()
        return self

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def url_for(self, relpath: str = "") -> str:
        rel = relpath.lstrip("/")
        return f"{self.url}/{rel}" if rel else self.url

    @property
    def request_count(self) -> int:
        with self._counts_lock:
            return self._counts["requests"]

    @property
    def get_count(self) -> int:
        with self._counts_lock:
            return self._counts["gets"]

    @property
    def file_get_count(self) -> int:
        """File-body GETs only (shard bytes actually re-fetched) —
        dir-index GETs and HEAD metadata excluded."""
        with self._counts_lock:
            return self._counts["file_gets"]

    def set_plan(self, plan) -> None:
        """Swap the fault plan between test phases (atomic attribute
        write; in-flight requests keep the plan they started with)."""
        self._httpd.plan = plan

    def set_latency(self, latency_s: float) -> None:
        self._httpd.latency_s = float(latency_s)

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "FaultingRangeServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve_directory(root: str, plan=None, latency_s: float = 0.0,
                    host: str = "127.0.0.1", port: int = 0) -> FaultingRangeServer:
    """Start a FaultingRangeServer over ``root`` on an ephemeral port and
    return it (already serving). The one-liner the tests, bench, and
    verify smoke use::

        with serve_directory(local_dir, plan=plan) as srv:
            ds = TFRecordDataset(srv.url_for("ds"), ...)
    """
    return FaultingRangeServer(
        root, plan=plan, latency_s=latency_s, host=host, port=port
    ).start()
