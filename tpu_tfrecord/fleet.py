"""Cluster flight recorder: telemetry spool, aggregation, and the fleet
verdict — the cross-process half of the observability plane.

The single-process flight recorder (tpu_tfrecord.telemetry) explains ONE
process's epoch. The disaggregated data service (ROADMAP #1, per "tf.data
service: A Case for Disaggregating ML Input Data Processing", PAPERS.md)
puts decode workers, a dispatcher, and trainer consumers in separate
processes on separate hosts — a slow epoch there is unexplainable unless
every process's counters, latency distributions, and verdicts merge into
one picture. Per the reproducible-pipelines paper (PAPERS.md), the
observability plane must exist BEFORE the distributed system it observes,
so the service lands debuggable on day one. Three pieces:

- **Telemetry spool** (``TelemetrySpool`` / ``acquire_spool``): every
  process with ``TFRecordOptions(telemetry_spool_dir=...)`` set
  periodically snapshots its metrics registry — cumulative counters,
  stage totals, gauges, and log-bucketed histogram bucket states (these
  merge EXACTLY across processes: fixed shared bucket layout) — plus a
  heartbeat, into one JSONL file per process in the spool directory.
  Writes are whole-file tmp+atomic-rename (bounded history, newest line
  is the authoritative cumulative snapshot), so a crash mid-write never
  leaves a truncated artifact for the aggregator to choke on. Each line
  is stamped with the writer's pid/host/role/trace id, reusing the
  writer's ``_JOB_META`` liveness-marker convention
  (io.writer.job_marker_payload — one schema owner). Spool off = the
  feature does not exist: zero new work on the hot path.

- **Aggregator** (``TelemetryAggregator``): merges every process's newest
  snapshot into cluster-level counters (exact sums), latency quantiles
  (exact bucket merges — real cluster p99s, not averages of per-process
  p99s), per-process gauges, and a cluster bound-ness verdict; flags
  processes whose heartbeat went stale (killed, wedged, partitioned) as
  dead; and serves one federated Prometheus ``/metrics`` page with
  ``host``/``pid``/``role`` labels on every family.

- **Fleet doctor** (tools/tfrecord_doctor.py ``fleet`` subcommand): the
  human entry point — per-process throughput/verdict lines, the dead
  list, and the cluster verdict from one spool directory; ``merge-trace``
  fuses the processes' Chrome traces into one Perfetto timeline
  (telemetry.merge_chrome_traces).

Counters (in the SPOOLING process's registry): ``fleet.spool_writes``
(snapshots landed), ``fleet.spool_errors`` (snapshot attempts that failed
— spooling is telemetry, it must never take the pipeline down).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpu_tfrecord import fs as _fs
from tpu_tfrecord import telemetry
from tpu_tfrecord.telemetry import (
    Histogram,
    TraceContext,
    atomic_write_bytes,
    boundness_verdict,
    quantiles_ms,
)

__all__ = [
    "SPOOL_SUFFIX",
    "DEFAULT_INTERVAL_S",
    "TelemetrySpool",
    "acquire_spool",
    "release_spool",
    "ProcessSnapshot",
    "read_spool",
    "read_spool_history",
    "FleetSnapshot",
    "TelemetryAggregator",
    "train_phase_shares",
]

#: Spool files are ``<host>-<pid>.spool.jsonl`` inside the spool dir; the
#: aggregator globs on the suffix, everything else in the dir is ignored.
SPOOL_SUFFIX = ".spool.jsonl"

#: Snapshot cadence when the option doesn't set one.
DEFAULT_INTERVAL_S = 1.0

#: Bounded per-process snapshot history (the newest line is cumulative and
#: authoritative; older lines exist for trend reads, and the bound keeps
#: the whole-file atomic rewrite O(1) per tick instead of O(ticks)).
DEFAULT_MAX_LINES = 256

#: Snapshot schema version stamped on every line.
SPOOL_VERSION = 1


def spool_path(spool_dir: str, ctx: TraceContext) -> str:
    return os.path.join(spool_dir, f"{ctx.host}-{ctx.pid}{SPOOL_SUFFIX}")


class TelemetrySpool:
    """Periodic atomic snapshot writer for ONE process's metrics registry.

    Same thread model as telemetry.Pulse: a daemon thread ticks every
    ``interval_s``; ``stop(final=True)`` lands one last snapshot so a
    short-lived process still leaves its totals behind. ``tick()`` is
    public for tests and for processes that want a snapshot NOW (e.g.
    just before exec'ing a successor).

    A snapshot line carries cumulative state, so the newest line
    supersedes every older one — the aggregator only ever reads the last
    parseable line per file. Writes go through tmp-file + atomic rename
    of the WHOLE (bounded) file: a crash mid-write leaves the previous
    complete file, never a truncated line.
    """

    def __init__(
        self,
        spool_dir: str,
        role: Optional[str] = None,
        interval_s: Optional[float] = None,
        metrics=None,
        context: Optional[TraceContext] = None,
        max_lines: int = DEFAULT_MAX_LINES,
        clock: Callable[[], float] = time.time,
    ):
        if metrics is None:
            from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
        self.metrics = metrics
        self.interval_s = (
            DEFAULT_INTERVAL_S if interval_s is None else float(interval_s)
        )
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self._clock = clock
        # an explicitly injected context is pinned (test seam); otherwise
        # snapshots FOLLOW the live process context, so a trace adopted
        # after the spool started (adopt_shared_trace_context after
        # iterator construction — nothing prevents that ordering) still
        # stamps every later snapshot, keeping spool lines, pulse lines,
        # and Chrome traces under one trace id and trace_id-scoped
        # aggregation from silently dropping the process
        self._pinned_context = context is not None
        if context is None:
            # role=None keeps whatever role the process already adopted
            # (adopt_from_env, adopt_shared_trace_context) — an explicit
            # role re-adopts, which is the telemetry_role option's job
            context = telemetry.current_context()
            if role is not None and context.role != role:
                context = telemetry.adopt(context.with_role(role))
        self.context = context
        if _fs.has_scheme(spool_dir):
            # os.path.abspath would silently mangle "gs://bucket/spool"
            # into a private local dir on every host — each worker would
            # look healthy while the aggregator finds an empty fleet
            raise ValueError(
                f"telemetry_spool_dir must be a local path (mount shared "
                f"storage locally instead); got {spool_dir!r}"
            )
        # normalize once: a relative spool_dir must not re-resolve against
        # a LATER cwd (a chdir between ticks, or between acquire/release,
        # would silently split the spool across directories)
        spool_dir = os.path.abspath(spool_dir)
        os.makedirs(spool_dir, exist_ok=True)
        self.spool_dir = spool_dir
        self.path = spool_path(spool_dir, context)
        self._lines: collections.deque = collections.deque(maxlen=max_lines)
        self._seq = 0
        # the snapshot's `created` stamp is the wall-window start that
        # throughput (records / (heartbeat - created)) divides by, and the
        # records are cumulative on the METRICS REGISTRY — so the epoch
        # must stick to the registry, not this spool instance: a second
        # spool over the same registry (release + re-acquire, back-to-back
        # iterators) keeps the original epoch instead of restarting the
        # window under lifetime totals and overstating the rate
        epoch = getattr(metrics, "_spool_epoch", None)
        if epoch is None:
            epoch = clock()
            try:
                metrics._spool_epoch = epoch
            except AttributeError:
                pass  # slotted/frozen registry: fall back to per-spool
        self._created = epoch
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tick_lock = threading.Lock()

    # -- snapshot ------------------------------------------------------------

    def snapshot(self, final: bool = False) -> Dict[str, Any]:
        """One cumulative snapshot line (not yet written). ``final`` marks
        a clean shutdown: the aggregator keeps a finished process out of
        the dead list forever, so a completed job never reads as a mass
        kill — only a process that STOPPED heartbeating without saying
        goodbye (SIGKILL, wedge, partition) goes stale."""
        # the writer's _JOB_META stamping convention is the one schema
        # owner for "which process wrote this, and is it alive" — reuse it
        # verbatim and extend with the trace identity
        from tpu_tfrecord.io.writer import job_marker_payload

        job = json.loads(job_marker_payload(created=self._created))
        if not self._pinned_context:
            # follow the LIVE process context: a shared trace adopted
            # after this spool started must stamp every later snapshot.
            # host/pid are restamped to this process at every adopt, so
            # the spool filename derived at init stays correct.
            self.context = telemetry.current_context()
        # identity comes from the adopted context (== this process in
        # production, injectable in tests) so the line always matches the
        # spool filename spool_path() derived from the same context
        job["pid"] = self.context.pid
        job["host"] = self.context.host
        job["role"] = self.context.role
        job["trace_id"] = self.context.trace_id
        job["span_id"] = self.context.span_id
        now = self._clock()
        job["heartbeat"] = now  # spool heartbeats ride the injectable clock
        stages: Dict[str, List[float]] = {}
        counters: Dict[str, int] = {}
        for name, (records, nbytes, batches, seconds) in sorted(
            self.metrics.raw_totals().items()
        ):
            if seconds == 0.0 and nbytes == 0:
                counters[name] = records
            else:
                stages[name] = [records, nbytes, batches, round(seconds, 6)]
        self._seq += 1
        return {
            "event": "spool",
            "v": SPOOL_VERSION,
            "seq": self._seq,
            "ts": round(now, 3),
            "interval_s": self.interval_s,
            **({"final": True} if final else {}),
            "job": job,
            "counters": counters,
            "stages": stages,
            "gauges": {
                k: round(v, 6) for k, v in sorted(self.metrics.gauges().items())
            },
            "hists": self.metrics.hist_states(),
        }

    def tick(self, final: bool = False) -> None:
        """Append one snapshot and atomically rewrite the spool file.
        Never raises: spooling is telemetry (``fleet.spool_errors`` counts
        failures so silent loss is still visible in the registry)."""
        with self._tick_lock:
            try:
                self._lines.append(
                    json.dumps(self.snapshot(final=final), sort_keys=True)
                )
                payload = ("\n".join(self._lines) + "\n").encode("utf-8")
                atomic_write_bytes(self.path, payload)
                self.metrics.count("fleet.spool_writes")
            except Exception:
                try:
                    self.metrics.count("fleet.spool_errors")
                except Exception:  # graftlint: swallow(the spool_errors counter itself failed; spooling never raises)
                    pass

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "TelemetrySpool":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tfr-spool"
            )
            self._thread.start()
        return self

    def stop(self, final: bool = True) -> None:
        """Stop the thread; ``final`` lands one last snapshot — marked as
        a clean shutdown, so the aggregator never flags this process dead
        — so the process's totals survive it. Idempotent."""
        already = self._stop.is_set()
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
        if final and not already:
            self.tick(final=True)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()


# One spool per (process, spool_dir): snapshots read the PROCESS-global
# metrics registry, so two concurrently-spooling iterators in one process
# would double-count every stage at aggregation. acquire/release refcount
# the singleton; the last release stops it with a final snapshot.
_SPOOLS: Dict[str, Tuple[TelemetrySpool, int]] = {}
_SPOOLS_LOCK = threading.Lock()


def acquire_spool(
    spool_dir: str,
    role: Optional[str] = None,
    interval_s: Optional[float] = None,
) -> TelemetrySpool:
    """Start (or join) the process's spool for ``spool_dir``. Refcounted:
    every ``acquire_spool`` must be paired with one ``release_spool``."""
    key = os.path.abspath(spool_dir)
    with _SPOOLS_LOCK:
        entry = _SPOOLS.get(key)
        if entry is not None:
            spool, refs = entry
            # joining an existing spool keeps ITS role/interval (the
            # snapshot stream is process-global); a caller who asked for
            # different settings must hear that they were not applied
            from tpu_tfrecord.metrics import logger

            if interval_s is not None and float(interval_s) != spool.interval_s:
                logger.warning(
                    "tfrecord.fleet spool for %s already ticking every "
                    "%gs; requested interval %gs ignored",
                    spool_dir, spool.interval_s, interval_s,
                )
            if role is not None and role != spool.context.role:
                logger.warning(
                    "tfrecord.fleet spool for %s already stamped with "
                    "role %r; requested role %r ignored",
                    spool_dir, spool.context.role, role,
                )
            _SPOOLS[key] = (spool, refs + 1)
            return spool
        spool = TelemetrySpool(spool_dir, role=role, interval_s=interval_s)
        spool.start()
        _SPOOLS[key] = (spool, 1)
        return spool


def release_spool(spool_dir: str) -> None:
    """Drop one reference; the last one stops the spool with a final
    snapshot. Unmatched releases are ignored (close + GC finalizer may
    both fire)."""
    key = os.path.abspath(spool_dir)
    with _SPOOLS_LOCK:
        entry = _SPOOLS.get(key)
        if entry is None:
            return
        spool, refs = entry
        if refs > 1:
            _SPOOLS[key] = (spool, refs - 1)
            return
        del _SPOOLS[key]
    spool.stop(final=True)


# ---------------------------------------------------------------------------
# Reading spools back
# ---------------------------------------------------------------------------


@dataclass
class ProcessSnapshot:
    """The newest parseable snapshot of one process's spool file."""

    path: str
    host: str
    pid: int
    role: str
    trace_id: Optional[str]
    heartbeat: float
    interval_s: float
    seq: int
    #: Spool start time on the writer's clock (job marker ``created``):
    #: ``heartbeat - created`` is the process's wall-clock observation
    #: window, the honest denominator for throughput (stage ``seconds``
    #: are cumulative BUSY seconds summed across worker threads).
    created: float = 0.0
    #: True when the newest snapshot is a clean-shutdown marker
    #: (TelemetrySpool.stop's final tick): the process FINISHED — the
    #: aggregator never flags it dead, however stale its heartbeat.
    final: bool = False
    counters: Dict[str, int] = field(default_factory=dict)
    stages: Dict[str, List[float]] = field(default_factory=dict)
    gauges: Dict[str, float] = field(default_factory=dict)
    hists: Dict[str, dict] = field(default_factory=dict)
    lines: int = 0
    skipped_lines: int = 0

    def heartbeat_age(self, now: float) -> float:
        return max(0.0, now - self.heartbeat)


def _snapshot_from_line(path: str, obj: Any) -> ProcessSnapshot:
    """Coerce one parsed spool line into a ProcessSnapshot, validating
    every field the aggregator will arithmetic on — raises ValueError/
    TypeError/KeyError on anything malformed (a version-skewed writer, a
    hand-edited file), so a bad LINE is skipped by read_spool instead of
    a bad FILE crashing the whole fleet aggregation later."""
    if obj.get("event") != "spool":
        raise ValueError(obj.get("event"))
    job = obj.get("job") or {}
    stages: Dict[str, List[float]] = {}
    for name, t in (obj.get("stages") or {}).items():
        if len(t) != 4:
            raise ValueError(f"stage {name!r}: expected 4 totals, got {t!r}")
        stages[str(name)] = [int(t[0]), int(t[1]), int(t[2]), float(t[3])]
    return ProcessSnapshot(
        path=path,
        host=str(job.get("host", "?")),
        pid=int(job.get("pid", 0)),
        role=str(job.get("role", "?")),
        trace_id=job.get("trace_id"),
        heartbeat=float(job.get("heartbeat", 0.0)),
        interval_s=float(obj.get("interval_s", DEFAULT_INTERVAL_S)),
        seq=int(obj.get("seq", 0)),
        created=float(job.get("created", 0.0)),
        final=bool(obj.get("final", False)),
        counters={
            str(k): int(v) for k, v in (obj.get("counters") or {}).items()
        },
        stages=stages,
        gauges={str(k): float(v) for k, v in (obj.get("gauges") or {}).items()},
        hists=dict(obj.get("hists") or {}),
    )


def read_spool(path: str) -> Optional[ProcessSnapshot]:
    """Parse one spool file: the newest valid line wins (lines are
    cumulative), so the scan runs newest-first and STOPS at the first
    valid line — aggregation and Prometheus scrapes pay one line's parse
    per process, not the whole bounded history's. Invalid lines — a torn
    write from a pre-atomic-rename crash, stray garbage, a version-skewed
    writer's unparseable shapes — are skipped and counted
    (``skipped_lines``; only lines newer than the winning one are ever
    tried), not fatal; a file with no valid line at all returns None."""
    try:
        with open(path, "rb") as fh:
            raw_lines = fh.read().splitlines()
    except OSError:
        return None
    raw_lines = [raw for raw in raw_lines if raw.strip()]
    skipped = 0
    for raw in reversed(raw_lines):
        try:
            newest = _snapshot_from_line(path, json.loads(raw))
        except (ValueError, TypeError, KeyError, AttributeError):
            skipped += 1
            continue
        newest.lines = len(raw_lines)
        newest.skipped_lines = skipped
        return newest
    return None


def read_spool_history(path: str) -> List[ProcessSnapshot]:
    """Parse EVERY valid line of one spool file, oldest first — the
    windowed time series the SLO engine's burn-rate math needs (each line
    is a cumulative snapshot stamped with the writer's ``ts``, so
    consecutive lines difference into per-interval deltas). Same skip
    semantics as ``read_spool``: invalid lines are dropped, never fatal;
    an unreadable file is an empty history. Each snapshot's ``heartbeat``
    carries its own line's timestamp (cumulative-at-that-moment), and
    ``skipped_lines`` on the last snapshot counts the file's bad lines."""
    try:
        with open(path, "rb") as fh:
            raw_lines = fh.read().splitlines()
    except OSError:
        return []
    out: List[ProcessSnapshot] = []
    skipped = 0
    for raw in raw_lines:
        if not raw.strip():
            continue
        try:
            snap = _snapshot_from_line(path, json.loads(raw))
        except (ValueError, TypeError, KeyError, AttributeError):
            skipped += 1
            continue
        out.append(snap)
    if out:
        out[-1].lines = len(out)
        out[-1].skipped_lines = skipped
    return out


@dataclass
class FleetSnapshot:
    """One merged cluster-level view over every process in a spool dir."""

    processes: List[ProcessSnapshot]
    alive: List[ProcessSnapshot]
    dead: List[ProcessSnapshot]
    counters: Dict[str, int]
    stages: Dict[str, List[float]]
    hists: Dict[str, Histogram]
    verdict: str
    occupancy: Optional[float]

    def quantiles(self) -> Dict[str, Dict[str, float]]:
        return {name: h.quantiles() for name, h in self.hists.items() if h.count}


class TelemetryAggregator:
    """Merge a spool directory into one cluster picture.

    - counters and stage totals SUM exactly (they are cumulative ints).
    - histograms merge bucket-exactly (telemetry.Histogram.merge_state) —
      the cluster p99 is the quantile of the union of observations, not a
      mean of per-process p99s.
    - gauges stay per-process (an occupancy averaged across processes
      before the verdict would hide one starved worker behind two full
      ones — the cluster verdict uses the mean of ALIVE processes'
      occupancy but the per-process values are preserved for the doctor).
    - liveness: a process whose newest heartbeat is older than
      ``stale_after_s`` (default: 2x its own declared snapshot interval)
      is dead — killed, wedged, or partitioned; its totals still count
      (they happened) but its staleness is first-class in the output.

    ``clock`` is injectable so staleness tests need no real waiting.
    """

    def __init__(
        self,
        spool_dir: str,
        stale_after_s: Optional[float] = None,
        clock: Callable[[], float] = time.time,
        trace_id: Optional[str] = None,
    ):
        if _fs.has_scheme(spool_dir):
            raise ValueError(
                f"spool_dir must be a local path (mount shared storage "
                f"locally instead); got {spool_dir!r}"
            )
        self.spool_dir = spool_dir
        self.stale_after_s = stale_after_s
        self._clock = clock
        #: When set, only spool files stamped with this trace id are
        #: merged — scopes a REUSED spool directory to one run (leftover
        #: files from a previous run carry its trace id, and merging them
        #: would silently double-count; the fleet line's ``trace_ids``
        #: list discloses a mixed directory when no filter is given).
        self.trace_id = trace_id

    def processes(
        self, roles: Optional[List[str]] = None
    ) -> List[ProcessSnapshot]:
        """Newest snapshot per spool file, name-sorted (deterministic).
        ``roles`` filters to processes stamped with one of the given
        telemetry roles — e.g. ``dispatcher --elastic --scaler-roles
        trainer`` scopes the fleet scaler's verdict to trainer processes
        only, so no other process's telemetry can ever vote on decode
        capacity. (Unscoped, the verdict already ignores processes with
        no occupancy gauge; the filter makes the boundary explicit
        rather than incidental.) Raises OSError when the spool dir
        itself is unreadable — an unreadable fleet must not look like an
        empty (healthy) one."""
        names = sorted(
            n for n in os.listdir(self.spool_dir) if n.endswith(SPOOL_SUFFIX)
        )
        snaps = []
        for name in names:
            snap = read_spool(os.path.join(self.spool_dir, name))
            if snap is not None and (
                self.trace_id is None or snap.trace_id == self.trace_id
            ) and (roles is None or snap.role in roles):
                snaps.append(snap)
        return snaps

    def _stale_after(self, snap: ProcessSnapshot) -> float:
        if self.stale_after_s is not None:
            return self.stale_after_s
        return 2.0 * snap.interval_s

    def aggregate(self, roles: Optional[List[str]] = None) -> FleetSnapshot:
        now = self._clock()
        procs = self.processes(roles)
        alive: List[ProcessSnapshot] = []
        dead: List[ProcessSnapshot] = []
        counters: Dict[str, int] = {}
        stages: Dict[str, List[float]] = {}
        hists: Dict[str, Histogram] = {}
        for p in procs:
            # a clean-shutdown (final) snapshot means the process FINISHED:
            # stale heartbeats only indict processes that never said goodbye
            (alive if p.final or p.heartbeat_age(now) <= self._stale_after(p)
             else dead).append(p)
            for name, v in p.counters.items():
                counters[name] = counters.get(name, 0) + v
            for name, totals in p.stages.items():
                agg = stages.setdefault(name, [0, 0, 0, 0.0])
                for i in range(4):
                    agg[i] += totals[i]
            for name, state in p.hists.items():
                # same resilience contract as read_spool: one process's
                # corrupt/foreign-layout histogram state loses that stage's
                # buckets for that process, never the whole fleet picture
                try:
                    hists.setdefault(name, Histogram()).merge_state(state)
                except (ValueError, TypeError, KeyError, IndexError):
                    continue
        # verdict from RUNNING processes when any exist: a finished
        # process's frozen last occupancy describes its exit moment, and
        # averaging it in would mask a starved still-running worker. With
        # NOTHING running the fleet is a post-mortem, and the finished
        # processes' exit-state occupancy is the only (and right) evidence.
        running = [p for p in alive if not p.final]
        occs = [
            p.gauges[telemetry.OCCUPANCY_GAUGE]
            for p in (running or alive)
            if telemetry.OCCUPANCY_GAUGE in p.gauges
        ]
        occupancy = sum(occs) / len(occs) if occs else None
        return FleetSnapshot(
            processes=procs,
            alive=alive,
            dead=dead,
            counters=counters,
            stages=stages,
            hists=hists,
            verdict=boundness_verdict(occupancy),
            occupancy=occupancy,
        )

    # -- federated Prometheus page -------------------------------------------

    def prometheus_text(self) -> str:
        """The whole fleet in Prometheus text exposition format: every
        sample labeled with its process's ``host``/``pid``/``role`` (sum
        over processes in PromQL: ``sum by (stage) (...)``), plus
        process-liveness families and cluster-exact latency quantiles
        from the merged histograms. One contiguous block per family —
        strict parsers reject interleaved families as duplicates (same
        rule as telemetry.prometheus_text)."""
        now = self._clock()
        snap = self.aggregate()
        alive = set(id(p) for p in snap.alive)
        lines: List[str] = []

        esc = telemetry.escape_label_value

        def labels(p: ProcessSnapshot, **extra: str) -> str:
            parts = [
                f'host="{esc(p.host)}"', f'pid="{p.pid}"',
                f'role="{esc(p.role)}"',
            ] + [f'{k}="{esc(v)}"' for k, v in extra.items()]
            return "{" + ",".join(parts) + "}"

        def family(fam: str, ftype: str, samples: List[str]) -> None:
            telemetry.append_family(lines, fam, ftype, samples)

        family(
            "tfrecord_process_up",
            "gauge",
            [
                f"tfrecord_process_up{labels(p)} {int(id(p) in alive)}"
                for p in snap.processes
            ],
        )
        family(
            "tfrecord_process_heartbeat_age_seconds",
            "gauge",
            [
                f"tfrecord_process_heartbeat_age_seconds{labels(p)} "
                f"{p.heartbeat_age(now):.3f}"
                for p in snap.processes
            ],
        )
        family(
            "tfrecord_stage_records_total",
            "counter",
            [
                f"tfrecord_stage_records_total{labels(p, stage=n)} {t[0]}"
                for p in snap.processes
                for n, t in sorted(p.stages.items())
            ]
            + [
                f"tfrecord_stage_records_total{labels(p, stage=n)} {v}"
                for p in snap.processes
                for n, v in sorted(p.counters.items())
            ],
        )
        family(
            "tfrecord_stage_bytes_total",
            "counter",
            [
                f"tfrecord_stage_bytes_total{labels(p, stage=n)} {t[1]}"
                for p in snap.processes
                for n, t in sorted(p.stages.items())
                if t[1]
            ],
        )
        family(
            "tfrecord_stage_seconds_total",
            "counter",
            [
                f"tfrecord_stage_seconds_total{labels(p, stage=n)} {t[3]:.6f}"
                for p in snap.processes
                for n, t in sorted(p.stages.items())
                if t[3]
            ],
        )
        family(
            "tfrecord_gauge",
            "gauge",
            [
                f"tfrecord_gauge{labels(p, name=n)} {v:.6g}"
                for p in snap.processes
                for n, v in sorted(p.gauges.items())
            ],
        )
        family(
            "tfrecord_fleet_latency_seconds",
            "summary",
            telemetry.summary_family_lines(
                "tfrecord_fleet_latency_seconds",
                (
                    (f'stage="{esc(name)}"', q)
                    for name, q in sorted(snap.quantiles().items())
                    # dimensionless diagnostic hists (moe.*, pipeline.*)
                    # are fractions, not seconds — a latency family must
                    # not carry them
                    if telemetry.is_latency_hist(name)
                ),
            ),
        )
        # Exemplars ride a dedicated gauge family (value = the exemplared
        # observation in seconds) rather than OpenMetrics `# {...}` sample
        # suffixes: the text-format 0.0.4 parsers the existing pages pin
        # would reject the suffix syntax. `le` carries the bucket's upper
        # bound so a dashboard can join an exemplar to the quantile family.
        family(
            "tfrecord_fleet_latency_exemplar_seconds",
            "gauge",
            [
                "tfrecord_fleet_latency_exemplar_seconds{"
                f'stage="{esc(name)}",le="{Histogram.bucket_le(idx):.6g}",'
                f'trace_id="{esc(t)}",span_id="{esc(s)}"'
                "} " + f"{v:.6g}"
                for name, h in sorted(snap.hists.items())
                if telemetry.is_latency_hist(name)
                for idx, (t, s, v) in sorted(h.exemplars.items())
            ],
        )
        return "\n".join(lines) + "\n"

    def serve(self, port: int):
        """Serve the federated page on 127.0.0.1:PORT (stdlib HTTP, same
        per-port server table as the single-process exporter — use
        telemetry.exporter_address/shutdown_exporter with the same
        requested port)."""
        return telemetry.serve_text_endpoint(
            port, self.prometheus_text, kind="fleet"
        )


def quantiles_ms_from_states(hists: Dict[str, dict]) -> Dict[str, Dict[str, float]]:
    """Per-stage p50/p90/p99 in ms from spooled histogram states — the
    same output shape as telemetry.quantiles_ms, for per-process doctor
    lines."""
    return quantiles_ms(
        {
            name: Histogram.from_states([state]).quantiles()
            for name, state in hists.items()
        }
    )


def train_phase_shares(snap: ProcessSnapshot) -> Optional[Dict[str, float]]:
    """A trainer's step-phase shares from its spool snapshot, or None for
    a process that never recorded the train phases (a reader/worker).

    Prefers the WINDOWED ``train.share.<phase>`` gauges the harness
    publishes (the recent regime — what the verdict should describe);
    falls back to shares computed from the cumulative ``train.<phase>``
    stage seconds (lifetime average) for trainers that died before a
    window completed. Keys are telemetry.TRAIN_PHASES entries."""
    gauges = {
        phase: snap.gauges[telemetry.TRAIN_SHARE_PREFIX + phase]
        for phase in telemetry.TRAIN_PHASES
        if telemetry.TRAIN_SHARE_PREFIX + phase in snap.gauges
    }
    if gauges:
        return gauges
    seconds = {
        phase: snap.stages[telemetry.TRAIN_STAGE_PREFIX + phase][3]
        for phase in telemetry.TRAIN_PHASES
        if telemetry.TRAIN_STAGE_PREFIX + phase in snap.stages
    }
    total = sum(seconds.values())
    if total <= 0:
        return None
    return {phase: s / total for phase, s in seconds.items()}
