"""Error-budget SLO engine: declarative objectives + multi-window
multi-burn-rate alerts over the fleet telemetry spool.

``tfrecord_doctor serve`` judges the serving tier from a point-in-time
p99 against ``--slo-ms`` — good for "is it slow NOW", useless for "are we
burning this month's error budget fast enough to page someone". This
module adds the standard SRE formulation on top of the counters and
histograms the fleet already spools:

- An **Objective** is a target fraction of good requests:
  *availability* = 1 − (sheds + deadline misses) / attempts, or
  *latency* = fraction of requests completing under a target, computed
  bucket-exactly from the stage histogram (a request is "good" only when
  its whole bucket's upper bound sits at or under the target — the
  estimate can never flatter the tail).
- The **burn rate** over a window is ``error_rate / (1 − target)``:
  1.0 means the budget drains exactly at the sustainable pace, 14.4
  means a 30-day budget is gone in ~2 days.
- A **BurnWindow** alert fires only when BOTH its long and its short
  window burn at or above the threshold (the classic multi-window
  multi-burn-rate rule: the long window proves it is sustained, the
  short window proves it is still happening — no paging on a stale
  spike). The defaults are the fast-page (1 h / 5 m at 14.4x) and
  slow-ticket (6 h / 30 m at 6x) pair; ``scaled()`` shrinks them so
  tests run in milliseconds of fake-clock time.

The engine consumes CUMULATIVE totals (exactly what the spool lines and
``Metrics.raw_totals`` carry) into a bounded ring of samples; windowed
deltas come from differencing the newest sample against the newest
sample at or before the window start. Counters are cumulative from
process start, so a window older than the whole ring honestly anchors
at zero. The clock is injectable throughout — burn-rate pins need no
real waiting.
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

from tpu_tfrecord import fleet as _fleet
from tpu_tfrecord.telemetry import Histogram

__all__ = [
    "Objective",
    "BurnWindow",
    "DEFAULT_WINDOWS",
    "DEFAULT_OBJECTIVES",
    "SloEngine",
    "burn_rate",
    "fleet_samples",
    "engine_from_spool",
]


@dataclass(frozen=True)
class Objective:
    """One declarative objective.

    ``kind`` is ``"availability"`` (good = completed without shed or
    deadline miss) or ``"latency"`` (good = completed under
    ``latency_ms``); ``target`` is the good fraction promised (0.999 =
    "three nines"). ``stage`` names the latency histogram a latency
    objective reads."""

    kind: str
    target: float
    latency_ms: Optional[float] = None
    stage: str = "serve.latency"

    def __post_init__(self) -> None:
        if self.kind not in ("availability", "latency"):
            raise ValueError(f"unknown objective kind {self.kind!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {self.target!r}")
        if self.kind == "latency" and (
            self.latency_ms is None or self.latency_ms <= 0
        ):
            raise ValueError("latency objective needs latency_ms > 0")

    @property
    def spec(self) -> str:
        if self.kind == "latency":
            return f"latency:{self.target:g}:{self.latency_ms:g}"
        return f"availability:{self.target:g}"

    @classmethod
    def parse(cls, spec: str) -> "Objective":
        """``availability:0.999`` or ``latency:0.95:250`` (ms)."""
        parts = spec.split(":")
        try:
            if parts[0] == "availability" and len(parts) == 2:
                return cls(kind="availability", target=float(parts[1]))
            if parts[0] == "latency" and len(parts) == 3:
                return cls(
                    kind="latency",
                    target=float(parts[1]),
                    latency_ms=float(parts[2]),
                )
        except ValueError as e:
            raise ValueError(f"bad objective {spec!r}: {e}") from e
        raise ValueError(
            f"bad objective {spec!r} (want availability:TARGET or "
            f"latency:TARGET:MS)"
        )

    def bad_total(
        self, counters: Dict[str, int], hists: Dict[str, Any]
    ) -> Tuple[int, int]:
        """(bad, total) cumulative pair from one snapshot's totals."""
        if self.kind == "availability":
            ok = int(counters.get("serve.requests", 0))
            sheds = int(counters.get("serve.rejected", 0))
            misses = int(counters.get("serve.deadline_expired", 0))
            return sheds + misses, ok + sheds + misses
        state = hists.get(self.stage)
        if state is None:
            return 0, 0
        hist = state if isinstance(state, Histogram) else (
            Histogram.from_states([state])
        )
        limit_s = float(self.latency_ms) / 1e3
        good = sum(
            c
            for idx, c in enumerate(hist.counts)
            if c and Histogram.bucket_le(idx) <= limit_s
        )
        return hist.count - good, hist.count


@dataclass(frozen=True)
class BurnWindow:
    """A (long, short) burn-rate alert pair: fires when both windows
    burn at or above ``threshold``."""

    name: str
    long_s: float
    short_s: float
    threshold: float

    def scaled(self, factor: float) -> "BurnWindow":
        """The same alert shape at ``factor`` x the window lengths —
        tests scale hours down to fake-clock seconds without changing
        the thresholds under pin."""
        return replace(
            self, long_s=self.long_s * factor, short_s=self.short_s * factor
        )


#: The standard SRE fast-page / slow-ticket pair.
DEFAULT_WINDOWS: Tuple[BurnWindow, ...] = (
    BurnWindow("fast", long_s=3600.0, short_s=300.0, threshold=14.4),
    BurnWindow("slow", long_s=21600.0, short_s=1800.0, threshold=6.0),
)

#: What ``doctor slo`` evaluates when no ``--objective`` is given: three
#: nines of availability, 95% of requests under 250 ms.
DEFAULT_OBJECTIVES: Tuple[Objective, ...] = (
    Objective(kind="availability", target=0.999),
    Objective(kind="latency", target=0.95, latency_ms=250.0),
)


def burn_rate(bad: float, total: float, target: float) -> float:
    """``error_rate / (1 − target)`` — 0.0 with no traffic (an idle
    window burns nothing)."""
    if total <= 0:
        return 0.0
    return (bad / total) / (1.0 - target)


@dataclass
class _Sample:
    ts: float
    #: Per-objective cumulative (bad, total), indexed like the engine's
    #: objective tuple.
    pairs: List[Tuple[int, int]] = field(default_factory=list)


class SloEngine:
    """Bounded ring of cumulative samples + burn-rate evaluation.

    Feed it cumulative totals (``observe``) at whatever cadence the
    spool or pulse runs; ``evaluate`` answers with per-objective budget
    remaining, per-window burn rates, and a verdict in
    {"healthy", "slow_burn", "fast_burn", "no_data"} (worst window that
    alerts wins; fast beats slow regardless of declaration order)."""

    def __init__(
        self,
        objectives: Sequence[Objective] = DEFAULT_OBJECTIVES,
        windows: Sequence[BurnWindow] = DEFAULT_WINDOWS,
        ring: int = 4096,
        clock: Callable[[], float] = time.time,
    ):
        if not objectives:
            raise ValueError("need at least one objective")
        if not windows:
            raise ValueError("need at least one burn window")
        self.objectives = tuple(objectives)
        self.windows = tuple(windows)
        self._clock = clock
        self._ring: Deque[_Sample] = deque(maxlen=ring)

    def observe(
        self,
        counters: Dict[str, int],
        hists: Optional[Dict[str, Any]] = None,
        ts: Optional[float] = None,
    ) -> None:
        """Fold one CUMULATIVE snapshot (counter totals + histogram
        states, e.g. a spool line or ``Metrics.raw_totals`` +
        ``hist_states``) into the ring at time ``ts`` (engine clock when
        omitted). Out-of-order samples are dropped — the ring is a time
        series, and cumulative totals older than the newest sample carry
        no new information."""
        ts = self._clock() if ts is None else float(ts)
        if self._ring and ts < self._ring[-1].ts:
            return
        hists = hists or {}
        self._ring.append(
            _Sample(
                ts=ts,
                pairs=[o.bad_total(counters, hists) for o in self.objectives],
            )
        )

    def _anchor(self, start_ts: float, idx: int) -> Tuple[int, int]:
        """Cumulative (bad, total) at the newest sample at or before
        ``start_ts`` — (0, 0) when the window opens before the whole
        ring (counters are cumulative from zero, so the honest anchor
        for a window older than the process is the origin)."""
        best: Tuple[int, int] = (0, 0)
        for sample in self._ring:
            if sample.ts > start_ts:
                break
            best = sample.pairs[idx]
        return best

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = self._clock() if now is None else float(now)
        if not self._ring:
            return {"now": now, "verdict": "no_data", "objectives": []}
        newest = self._ring[-1]
        rank = {"healthy": 0, "slow_burn": 1, "fast_burn": 2}
        out: List[Dict[str, Any]] = []
        overall = "healthy"
        for idx, obj in enumerate(self.objectives):
            bad_now, total_now = newest.pairs[idx]
            longest = max(w.long_s for w in self.windows)
            anchor = self._anchor(now - longest, idx)
            budget_bad = bad_now - anchor[0]
            budget_total = total_now - anchor[1]
            allowed = (1.0 - obj.target) * budget_total
            if allowed > 0:
                remaining = min(1.0, 1.0 - budget_bad / allowed)
            else:
                remaining = 1.0 if budget_bad == 0 else 0.0
            verdict = "healthy"
            wreports: List[Dict[str, Any]] = []
            for w in self.windows:
                burns = []
                for span_s in (w.long_s, w.short_s):
                    a = self._anchor(now - span_s, idx)
                    burns.append(
                        burn_rate(
                            bad_now - a[0], total_now - a[1], obj.target
                        )
                    )
                alerting = burns[0] >= w.threshold and burns[1] >= w.threshold
                wreports.append(
                    {
                        "name": w.name,
                        "long_s": w.long_s,
                        "short_s": w.short_s,
                        "threshold": w.threshold,
                        "long_burn": burns[0],
                        "short_burn": burns[1],
                        "alerting": alerting,
                    }
                )
                if alerting:
                    candidate = (
                        "fast_burn" if w.name == "fast" else "slow_burn"
                    )
                    if rank[candidate] > rank[verdict]:
                        verdict = candidate
            out.append(
                {
                    "objective": obj.spec,
                    "kind": obj.kind,
                    "target": obj.target,
                    "latency_ms": obj.latency_ms,
                    "bad": budget_bad,
                    "total": budget_total,
                    "budget_remaining": remaining,
                    "windows": wreports,
                    "verdict": verdict,
                }
            )
            if rank[verdict] > rank[overall]:
                overall = verdict
        return {"now": now, "verdict": overall, "objectives": out}

    def publish(self, metrics: Any, now: Optional[float] = None) -> Dict[str, Any]:
        """Evaluate and land the result on a ``Metrics`` registry as
        ``slo.<kind>.budget_remaining`` / ``slo.<kind>.<window>_burn``
        gauges (dynamic ``slo.`` gauge prefix in the vocabulary), so the
        spool ships the SLO state alongside the raw counters it was
        computed from. Returns the evaluation."""
        report = self.evaluate(now)
        for entry in report["objectives"]:
            prefix = f"slo.{entry['kind']}"
            metrics.gauge(
                f"{prefix}.budget_remaining", entry["budget_remaining"]
            )
            for w in entry["windows"]:
                metrics.gauge(f"{prefix}.{w['name']}_burn", w["long_burn"])
        return report


# ---------------------------------------------------------------------------
# Fleet spool -> time series
# ---------------------------------------------------------------------------


def fleet_samples(
    spool_dir: str, trace_id: Optional[str] = None
) -> List[Tuple[float, Dict[str, int], Dict[str, Histogram]]]:
    """The cluster-wide cumulative time series from a spool directory:
    at each timestamp any process heartbeat, (ts, summed counters,
    bucket-exactly merged histograms) over every process's NEWEST line
    at or before ts — the same merge discipline as
    ``TelemetryAggregator.aggregate`` applied per point in time.
    ``trace_id`` scopes a reused spool dir to one run. Raises OSError
    when the dir itself is unreadable (an unreadable fleet must not look
    idle)."""
    histories: List[List[_fleet.ProcessSnapshot]] = []
    for name in sorted(os.listdir(spool_dir)):
        if not name.endswith(_fleet.SPOOL_SUFFIX):
            continue
        history = [
            snap
            for snap in _fleet.read_spool_history(
                os.path.join(spool_dir, name)
            )
            if trace_id is None or snap.trace_id == trace_id
        ]
        if history:
            histories.append(history)
    timestamps = sorted(
        {snap.heartbeat for history in histories for snap in history}
    )
    series: List[Tuple[float, Dict[str, int], Dict[str, Histogram]]] = []
    for ts in timestamps:
        counters: Dict[str, int] = {}
        hists: Dict[str, Histogram] = {}
        for history in histories:
            newest: Optional[_fleet.ProcessSnapshot] = None
            for snap in history:
                if snap.heartbeat <= ts:
                    newest = snap
                else:
                    break
            if newest is None:
                continue
            for cname, v in newest.counters.items():
                counters[cname] = counters.get(cname, 0) + v
            for hname, state in newest.hists.items():
                # same per-hist resilience as the aggregator: one bad
                # state loses that stage for that process at that point,
                # never the series
                try:
                    hists.setdefault(hname, Histogram()).merge_state(state)
                except (ValueError, TypeError, KeyError, IndexError):
                    continue
        series.append((ts, counters, hists))
    return series


def engine_from_spool(
    spool_dir: str,
    objectives: Sequence[Objective] = DEFAULT_OBJECTIVES,
    windows: Sequence[BurnWindow] = DEFAULT_WINDOWS,
    trace_id: Optional[str] = None,
    clock: Callable[[], float] = time.time,
) -> Optional[SloEngine]:
    """An engine pre-fed with a spool directory's whole fleet series —
    what ``tfrecord_doctor slo`` evaluates. None when the directory
    holds no (matching) snapshots, so the caller can distinguish "no
    fleet" (exit 2) from "fleet is idle" (healthy, no traffic)."""
    series = fleet_samples(spool_dir, trace_id=trace_id)
    if not series:
        return None
    engine = SloEngine(
        objectives=objectives,
        windows=windows,
        ring=max(len(series), 16),
        clock=clock,
    )
    for ts, counters, hists in series:
        engine.observe(counters, hists, ts=ts)
    return engine
