"""Pipeline flight recorder: span tracing, latency histograms, telemetry
pulse, and the producer/consumer bound-ness verdict.

The reference has no observability of its own (SURVEY.md §5: tracing ABSENT
— it rides on Spark's UI), and `Metrics` only aggregates per-stage TOTALS:
after an epoch you know decode took N seconds, but not the latency
distribution, which shard was the straggler, or whether the pipeline was
producer- or consumer-bound at any moment. tf.data's auto-tuning and the
tf.data service rest on exactly this kind of per-op timing instrumentation
(PAPERS.md) — a slow epoch should be explainable without attaching a
profiler. Four pieces:

- **Span tracing** (``SpanRecorder``): a thread-safe, bounded ring buffer
  of (name, begin, duration, thread, attrs) records, written through the
  ``span(name, **attrs)`` context manager and ``instant(name, **attrs)``
  point events. Opt-in via ``TFRecordOptions(trace="on")`` — when off, the
  module-level ``span()``/``instant()`` return a shared no-op without
  taking any lock (one attribute read on the hot path). Exportable as
  Chrome trace-event JSON (``to_chrome_trace``/``save_chrome_trace``) —
  loadable in Perfetto / chrome://tracing. Spans are mirrored onto the
  jax-profiler timeline through the existing ``tracing.trace`` annotations
  every instrumented site already holds, so xprof captures show the same
  regions.

- **Latency histograms** (``Histogram``): log-bucketed (~19% geometric
  buckets → quantile relative error ≤ ~10%), folded into ``Metrics`` via
  ``Metrics.observe``/the ``timed`` context manager, so every timed stage
  (shard open, slab read, chunk decode, cache serve, write/commit) grows a
  p50/p90/p99 next to its totals and stragglers stop hiding inside means.

- **Telemetry pulse** (``Pulse``): a background reporter emitting one
  machine-parseable JSON line per interval — per-interval stage
  throughputs, cumulative counters, histogram quantiles, gauges (prefetch
  queue depth, in-flight decode workers, backpressure occupancy), and the
  bound-ness verdict. Opt-in via ``TFRecordOptions(pulse_interval_s=...)``;
  an optional stdlib-HTTP Prometheus text endpoint
  (``TFRecordOptions(telemetry_port=...)`` / ``ensure_exporter``) serves
  the same registry for scraping. Pulse ticks are also the pipeline's
  ACTUATION points: registered observers (``add_observer``) see each
  payload before it is emitted and may merge fields into the line — the
  closed-loop autotuner (tpu_tfrecord.autotune) runs this way, so every
  knob decision lands in the same trace as the interval it was made from.

- **Bound-ness verdict** (``boundness_verdict``): computed from the
  prefetch queue's average fill fraction, sampled by the consumer. A queue
  that is nearly always FULL means decode keeps ahead of the consumer —
  the pipeline is consumer-bound (the device/training step is the
  bottleneck; the BASELINE.md goal state). Nearly always EMPTY means the
  consumer drains batches faster than decode produces them —
  producer-bound (speed up the input pipeline).

The offline complement is ``tools/tfrecord_doctor.py report DATA_DIR``:
run N batches with tracing on and print the stage breakdown, slowest
shards, straggler ratio, and the verdict.

This module deliberately imports nothing from the rest of the package at
module level (stdlib only; the default-registry lookups import
``metrics`` lazily), so every layer — metrics, io, cache, stall — can
import it without cycles.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import socket
import threading
import time
import uuid
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Histogram",
    "SpanRecorder",
    "Pulse",
    "RECORDER",
    "TraceContext",
    "current_context",
    "adopt",
    "adopt_from_env",
    "span",
    "instant",
    "record_span",
    "record_instant",
    "enable",
    "disable",
    "boundness_verdict",
    "verdict_from_metrics",
    "OccupancyEma",
    "quantiles_ms",
    "merge_chrome_traces",
    "atomic_write_bytes",
    "prometheus_text",
    "ensure_exporter",
    "serve_text_endpoint",
    "exporter_address",
    "shutdown_exporter",
]


# ---------------------------------------------------------------------------
# Latency histograms
# ---------------------------------------------------------------------------


class Histogram:
    """Log-bucketed latency histogram with quantile estimation.

    Buckets grow geometrically by ``2**0.25`` (~19% per bucket) from a
    100 ns floor, spanning 100 ns .. ~1.9 h in 144 fixed buckets — so one
    histogram is a flat int list, O(1) to observe and cheap to snapshot.
    Quantiles interpolate at the log-midpoint of the selected bucket and
    clamp to the observed [min, max], bounding the relative error at
    ``sqrt(2**0.25) - 1`` ≈ 9.1% (pinned against a reference sort in
    tests/test_telemetry.py).

    NOT internally locked: the owner (``Metrics``) serializes access under
    its own lock so one observation costs one lock acquisition total.
    """

    _MIN = 1e-7  # 100 ns floor: anything faster is bucket 0
    _LOG2_GROWTH = 0.25  # buckets grow by 2**0.25 per step
    _NBUCKETS = 144  # 144 * 0.25 = 36 octaves above _MIN (~1.9 h)

    __slots__ = ("counts", "count", "total", "min", "max", "exemplars")

    def __init__(self) -> None:
        self.counts = [0] * self._NBUCKETS
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = 0.0
        # bucket index -> (trace_id, span_id, value): the LAST exemplar
        # observed into that bucket. Bounded by construction (one entry
        # per populated bucket, <= _NBUCKETS) and carried bucket-exactly
        # through state()/merge_state() so fleet merges keep the pointer
        # from a tail bucket to the trace that filled it.
        self.exemplars: Dict[int, Tuple[str, str, float]] = {}

    def bucket_index(self, value: float) -> int:
        if value <= self._MIN:
            return 0
        return min(
            self._NBUCKETS - 1,
            1 + int(math.log2(value / self._MIN) / self._LOG2_GROWTH),
        )

    @classmethod
    def bucket_le(cls, idx: int) -> float:
        """Inclusive upper bound (seconds) of bucket ``idx`` — the ``le``
        label when a bucket is rendered on a Prometheus page."""
        if idx <= 0:
            return cls._MIN
        return cls._MIN * 2 ** (idx * cls._LOG2_GROWTH)

    def observe(
        self,
        value: float,
        exemplar: Optional[Tuple[str, str]] = None,
    ) -> None:
        idx = self.bucket_index(value)
        self.counts[idx] += 1
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if exemplar is not None:
            self.exemplars[idx] = (
                str(exemplar[0]), str(exemplar[1]), float(value)
            )

    def exemplar_at(self, q: float) -> Optional[Dict[str, Any]]:
        """The exemplar nearest the quantile-``q`` bucket: the exemplar of
        the highest populated bucket at or below where ``quantile(q)``
        lands (tail observations overwrite last-wins, so for q near 1 this
        is 'the trace that filled the top bucket'). None when no exemplar
        was ever attached at or below that bucket."""
        if self.count == 0 or not self.exemplars:
            return None
        rank = q * self.count
        cum = 0
        target = self._NBUCKETS - 1
        for idx, c in enumerate(self.counts):
            cum += c
            if cum >= rank and c:
                target = idx
                break
        best = None
        for idx, ex in self.exemplars.items():
            if idx <= target and (best is None or idx > best):
                best = idx
        if best is None:
            return None
        trace_id, span_id, value = self.exemplars[best]
        return {
            "bucket": best,
            "trace_id": trace_id,
            "span_id": span_id,
            "value": value,
        }

    def quantile(self, q: float) -> Optional[float]:
        """Estimated value at quantile ``q`` in [0, 1] (None when empty)."""
        if self.count == 0:
            return None
        rank = q * self.count
        cum = 0
        for idx, c in enumerate(self.counts):
            cum += c
            if cum >= rank and c:
                if idx == 0:
                    est = self._MIN
                else:
                    # log-midpoint of the bucket [g**(idx-1), g**idx) * _MIN
                    est = self._MIN * 2 ** ((idx - 0.5) * self._LOG2_GROWTH)
                return min(max(est, self.min), self.max)
        return self.max

    def quantiles(self) -> Dict[str, float]:
        """The standard p50/p90/p99 snapshot (seconds), plus count/mean."""
        if self.count == 0:
            return {}
        return {
            "p50_s": self.quantile(0.50),
            "p90_s": self.quantile(0.90),
            "p99_s": self.quantile(0.99),
            "count": self.count,
            "mean_s": self.total / self.count,
        }

    # -- cross-process export/merge ------------------------------------------
    #
    # The bucket layout is FIXED (same floor, growth, count in every
    # process), so per-process histograms merge exactly: bucket counts
    # add, min/max fold — the merged histogram is bucket-identical to one
    # histogram fed every process's observations (pinned by a property
    # test in tests/test_fleet.py). This is what makes cluster-level
    # quantiles from per-process spool snapshots honest rather than an
    # average-of-quantiles approximation.

    def state(self) -> Dict[str, Any]:
        """JSON-serializable snapshot: sparse bucket counts + count/total/
        min/max. The layout params ride along so a merge across versions
        with a different bucket geometry fails loudly instead of blending
        incompatible buckets."""
        state: Dict[str, Any] = {
            "buckets": {
                str(i): c for i, c in enumerate(self.counts) if c
            },
            "count": self.count,
            "total": self.total,
            "min": self.min if self.count else None,
            "max": self.max,
            "layout": [self._MIN, self._LOG2_GROWTH, self._NBUCKETS],
        }
        if self.exemplars:
            # omitted when empty: pre-exemplar snapshots and exemplar-free
            # histograms serialize byte-identically to before
            state["exemplars"] = {
                str(i): [t, s, v]
                for i, (t, s, v) in sorted(self.exemplars.items())
            }
        return state

    def merge_state(self, state: Dict[str, Any]) -> None:
        """Fold one ``state()`` snapshot in (exact: fixed shared buckets)."""
        if not isinstance(state, dict):
            raise TypeError(
                f"histogram state must be a mapping, got {type(state).__name__}"
            )
        layout = state.get("layout")
        if layout is not None and list(layout) != [
            self._MIN, self._LOG2_GROWTH, self._NBUCKETS,
        ]:
            raise ValueError(
                f"histogram bucket layout mismatch: {layout} vs "
                f"{[self._MIN, self._LOG2_GROWTH, self._NBUCKETS]}"
            )
        buckets = state.get("buckets") or {}
        if not isinstance(buckets, dict):
            raise TypeError(
                f"histogram buckets must be a mapping, got {type(buckets).__name__}"
            )
        for idx, c in buckets.items():
            i = int(idx)
            if not 0 <= i < self._NBUCKETS:
                # a negative index would silently wrap into the tail bucket
                raise ValueError(f"histogram bucket index out of range: {i}")
            self.counts[i] += int(c)
        self.count += int(state.get("count", 0))
        self.total += float(state.get("total", 0.0))
        smin = state.get("min")
        if smin is not None and smin < self.min:
            self.min = smin
        smax = state.get("max")
        if smax is not None and smax > self.max:
            self.max = smax
        exemplars = state.get("exemplars") or {}
        if not isinstance(exemplars, dict):
            raise TypeError(
                f"histogram exemplars must be a mapping, got "
                f"{type(exemplars).__name__}"
            )
        for idx, ex in exemplars.items():
            i = int(idx)
            if not 0 <= i < self._NBUCKETS:
                raise ValueError(f"exemplar bucket index out of range: {i}")
            trace_id, span_id, value = ex
            # last-wins across merge order; bucket COUNTS are untouched,
            # so exemplar-carrying states merge to the same quantiles as
            # exemplar-free ones
            self.exemplars[i] = (str(trace_id), str(span_id), float(value))

    @classmethod
    def from_states(cls, states: Iterable[Dict[str, Any]]) -> "Histogram":
        hist = cls()
        for st in states:
            hist.merge_state(st)
        return hist


# ---------------------------------------------------------------------------
# Cross-process trace context
# ---------------------------------------------------------------------------

#: Environment variable carrying a serialized TraceContext from a parent
#: process to its children (doctor subprocesses, multihost workers, future
#: data-service workers). ``adopt_from_env`` reads it; ``TraceContext.to_env``
#: produces the value to put in a child's environment.
TRACE_CONTEXT_ENV = "TFR_TRACE_CONTEXT"


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Identity of one process's slice of a distributed trace.

    ``trace_id`` is shared by every process participating in one logical
    run (a multihost job, a dispatcher + its decode workers); ``span_id``
    is this process's own root id, and ``parent_span_id`` names the root
    of the process that spawned/coordinated it (None at the root). role/
    host/pid identify the process for humans and for the spool aggregator
    — merged Perfetto timelines label tracks ``role@host:pid``.

    Plain JSON-serializable value: ``to_json``/``from_json`` round-trip
    it; ``to_env``/``adopt_from_env`` ship it across a process spawn via
    the ``TFR_TRACE_CONTEXT`` environment variable, the child minting its
    own span id and stamping its own host/pid (ids propagate, identities
    never do)."""

    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None
    role: str = "main"
    host: str = ""
    pid: int = 0

    @staticmethod
    def new(role: str = "main") -> "TraceContext":
        """A fresh root context for this process."""
        return TraceContext(
            trace_id=_new_id(),
            span_id=_new_id(),
            parent_span_id=None,
            role=role,
            host=socket.gethostname(),
            pid=os.getpid(),
        )

    def child(self, role: str) -> "TraceContext":
        """A context for a process THIS one spawns: same trace, new span
        id, this context's span as the parent. host/pid are left for the
        child to stamp at adoption (they describe the child, and the
        parent cannot know them)."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=_new_id(),
            parent_span_id=self.span_id,
            role=role,
            host="",
            pid=0,
        )

    def with_role(self, role: str) -> "TraceContext":
        return dataclasses.replace(self, role=role)

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(obj: Dict[str, Any]) -> "TraceContext":
        known = {f.name for f in dataclasses.fields(TraceContext)}
        return TraceContext(**{k: v for k, v in obj.items() if k in known})

    def to_env(self) -> Dict[str, str]:
        """{TFR_TRACE_CONTEXT: payload} to merge into a child process's
        environment — the child's ``adopt_from_env`` joins this trace."""
        return {TRACE_CONTEXT_ENV: json.dumps(self.to_json(), sort_keys=True)}

    def label(self) -> str:
        """The human track label merged timelines use: ``role@host:pid``."""
        return f"{self.role}@{self.host}:{self.pid}"


def current_context() -> TraceContext:
    """The process's trace context — created (and cached on the global
    recorder) on first use, so pulse lines and spool snapshots always
    carry host/pid/role even when nobody propagated a context in."""
    ctx = RECORDER.context
    if ctx is None:
        ctx = RECORDER.adopt(TraceContext.new())
    return ctx


def adopt(ctx: TraceContext) -> TraceContext:
    """Adopt ``ctx`` as this process's identity on the global recorder
    (host/pid re-stamped to the adopting process — identities never
    propagate, only ids do)."""
    return RECORDER.adopt(ctx)


def _adopt_child_of(obj: Any, role: Optional[str]) -> TraceContext:
    """Adopt a context that joins the trace ``obj`` (a parsed TraceContext
    JSON object) describes: keep the parent's trace id, record the
    parent's span as our parent, mint our own span id (host/pid stamped by
    ``adopt``). Raises on malformed payloads — callers own the degrade
    policy."""
    if not isinstance(obj, dict):
        # valid JSON that is not an object ('null', '[1]', '"x"')
        # is just as malformed as unparseable bytes
        raise ValueError(f"not a JSON object: {obj!r}")
    parent = TraceContext.from_json(obj)
    ctx = TraceContext(
        trace_id=parent.trace_id,
        span_id=_new_id(),
        parent_span_id=parent.span_id,
        role=role if role is not None else parent.role,
    )
    return RECORDER.adopt(ctx)


def adopt_from_env(
    role: Optional[str] = None, environ: Optional[Dict[str, str]] = None
) -> TraceContext:
    """Join the trace a parent process shipped via ``TFR_TRACE_CONTEXT``:
    the child keeps the parent's trace id, records the parent's span id as
    its parent, and mints its own span id / host / pid. Without the env
    var this is a fresh root context — subprocesses can call it
    unconditionally."""
    environ = os.environ if environ is None else environ
    raw = environ.get(TRACE_CONTEXT_ENV)
    if raw:
        try:
            return _adopt_child_of(json.loads(raw), role)
        except (ValueError, TypeError, KeyError, AttributeError):
            pass  # a malformed payload must not take the pipeline down
    return RECORDER.adopt(TraceContext.new(role if role is not None else "main"))


def adopt_child_from_json(
    obj: Any, role: Optional[str] = None
) -> TraceContext:
    """Join the trace of a coordinator that handed us its context over a
    WIRE payload rather than a spawn environment — the data-service worker
    adopting the dispatcher's trace at registration. Same semantics as
    ``adopt_from_env`` (ids propagate, identities never do); a malformed
    payload degrades to a fresh root, never raises."""
    try:
        return _adopt_child_of(obj, role)
    except (ValueError, TypeError, KeyError, AttributeError):
        return RECORDER.adopt(
            TraceContext.new(role if role is not None else "main")
        )


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` via tmp-file + atomic rename, so a crash
    mid-write never leaves a truncated/corrupt artifact behind for a
    reader (the spool aggregator, Perfetto) to choke on. The tmp name is
    pid-suffixed: two processes racing on one path each land a complete
    file, last rename wins."""
    tmp = f"{path}.tmp-{os.getpid()}-{_new_id()[:8]}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


# ---------------------------------------------------------------------------
# Span tracing
# ---------------------------------------------------------------------------


class _NoopSpan:
    """The shared disabled-path context manager: no state, no lock."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def set(self, **attrs) -> None:
        return None


_NOOP = _NoopSpan()


class _SpanCtx:
    """One live span: records (name, begin, duration, tid, attrs) into its
    recorder on exit. An exception propagating through the span marks it
    ``failed=1`` — error latency stays attributed to its stage."""

    __slots__ = ("_rec", "name", "attrs", "_t0")

    def __init__(self, rec: "SpanRecorder", name: str, attrs: Optional[dict]):
        self._rec = rec
        self.name = name
        self.attrs = attrs

    def set(self, **attrs) -> None:
        """Attach attrs discovered mid-span (row counts, byte counts)."""
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur = time.perf_counter_ns() - self._t0
        attrs = self.attrs
        if exc_type is not None:
            attrs = dict(attrs or (), failed=1)
        self._rec._record(self.name, self._t0, dur, attrs, "X")
        return None


class SpanRecorder:
    """Thread-safe bounded ring buffer of span/instant records.

    ``capacity`` bounds memory for arbitrarily long epochs: the buffer
    keeps the most recent ``capacity`` records and counts what it dropped
    (``dropped``) — a flight recorder, not an archive. ``enabled`` is a
    plain attribute read on the hot path; when False, the module-level
    ``span()``/``instant()`` return the shared no-op without touching this
    object's lock (pinned by tests/test_telemetry.py).
    """

    def __init__(self, capacity: int = 65536, enabled: bool = False):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.enabled = enabled
        self._lock = threading.Lock()
        # ring storage: fixed-size list + running sequence number
        self._ring: List[Optional[tuple]] = [None] * capacity
        self._seq = 0
        self.dropped = 0
        #: Adopted TraceContext (None until the process identifies itself
        #: via ``adopt``/``current_context``). Purely metadata: recording
        #: never reads it, so the hot path is unchanged.
        self.context: Optional[TraceContext] = None

    def adopt(self, ctx: TraceContext) -> TraceContext:
        """Adopt ``ctx`` as this recorder's process identity, re-stamping
        host/pid to the adopting process (a shipped context carries the
        PARENT's ids plus a role — never another process's identity)."""
        host = socket.gethostname()
        pid = os.getpid()
        if ctx.host != host or ctx.pid != pid:
            ctx = dataclasses.replace(ctx, host=host, pid=pid)
        self.context = ctx
        return ctx

    # -- recording -----------------------------------------------------------

    def span(self, name: str, **attrs) -> "_SpanCtx | _NoopSpan":
        if not self.enabled:
            return _NOOP
        return _SpanCtx(self, name, attrs or None)

    def instant(self, name: str, **attrs) -> None:
        if not self.enabled:
            return
        self._record(name, time.perf_counter_ns(), 0, attrs or None, "i")

    def _record(
        self,
        name: str,
        t0_ns: int,
        dur_ns: int,
        attrs: Optional[dict],
        ph: str,
        tid: Optional[int] = None,
    ) -> None:
        # ``tid`` override: per-request spans (serving) record onto a
        # synthetic lane per request id so concurrent requests render as
        # parallel tracks in Perfetto instead of overlapping X events on
        # one thread's track
        if tid is None:
            tid = threading.get_ident()
        with self._lock:
            seq = self._seq
            self._seq = seq + 1
            if seq >= self.capacity:
                self.dropped += 1
            self._ring[seq % self.capacity] = (name, t0_ns, dur_ns, tid, attrs, ph)

    # -- reading -------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return min(self._seq, self.capacity)

    def spans(self) -> List[tuple]:
        """Snapshot of retained records, oldest first:
        (name, t0_ns, dur_ns, tid, attrs, ph)."""
        with self._lock:
            seq = self._seq
            if seq <= self.capacity:
                return [r for r in self._ring[:seq]]
            start = seq % self.capacity
            return [
                r
                for r in (self._ring[start:] + self._ring[:start])
                if r is not None
            ]

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._seq = 0
            self.dropped = 0

    # -- export --------------------------------------------------------------

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The retained records as a Chrome trace-event JSON object
        (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU
        — the format Perfetto and chrome://tracing load). Durations are
        complete ("X") events; point events are instants ("i").

        Leads with process/thread metadata ("M") records — the process
        track is named from the adopted TraceContext (``role@host:pid``)
        and live pipeline threads get their Python thread names — so a
        ``merge_chrome_traces`` fusion of K per-process files renders as K
        labeled tracks in one Perfetto timeline. The adopted context also
        rides the top-level ``traceContext`` key (extra top-level keys are
        legal in the format), which is how the merger correlates files
        from different hosts that happen to reuse a pid."""
        ctx = self.context
        pid = ctx.pid if ctx is not None and ctx.pid else os.getpid()
        pname = ctx.label() if ctx is not None else f"tfrecord:{pid}"
        events: List[Dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": pname},
            }
        ]
        thread_names = {t.ident: t.name for t in threading.enumerate()}
        seen_tids = set()
        spans = self.spans()
        for rec in spans:
            tid = rec[3]
            if tid in seen_tids:
                continue
            seen_tids.add(tid)
            name = thread_names.get(tid)
            if name:  # best-effort: exited threads keep their bare ident
                events.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": pid,
                        "tid": tid,
                        "args": {"name": name},
                    }
                )
        for name, t0_ns, dur_ns, tid, attrs, ph in spans:
            ev: Dict[str, Any] = {
                "name": name,
                "cat": "tfrecord",
                "ph": ph,
                "ts": t0_ns / 1000.0,  # microseconds
                "pid": pid,
                "tid": tid,
            }
            if ph == "X":
                ev["dur"] = dur_ns / 1000.0
            else:
                ev["s"] = "t"  # thread-scoped instant
            if attrs:
                ev["args"] = attrs
            events.append(ev)
        out: Dict[str, Any] = {"traceEvents": events, "displayTimeUnit": "ms"}
        if ctx is not None:
            out["traceContext"] = ctx.to_json()
        return out

    def save_chrome_trace(self, path: str) -> None:
        """Export atomically (tmp + rename): a crash mid-dump must never
        leave a truncated JSON for Perfetto or the trace merger to choke
        on."""
        atomic_write_bytes(
            path, json.dumps(self.to_chrome_trace()).encode("utf-8")
        )


#: Process-global flight recorder — spans come from dataset iterators,
#: prefetch workers, writer pipeline threads, and the stall guard, so the
#: ring is shared (one timeline). ``TFRecordOptions(trace="on")`` enables it
#: at dataset/writer construction; it stays on until ``disable()``.
RECORDER = SpanRecorder()


def span(name: str, **attrs):
    """Record a duration span on the global recorder; a shared no-op (no
    lock, no allocation beyond the caller's kwargs) when tracing is off."""
    rec = RECORDER
    if not rec.enabled:
        return _NOOP
    return _SpanCtx(rec, name, attrs or None)


def instant(name: str, **attrs) -> None:
    """Record a point event (stall, hedge, retry, watchdog restart)."""
    rec = RECORDER
    if rec.enabled:
        rec._record(name, time.perf_counter_ns(), 0, attrs or None, "i")


def record_span(
    name: str, t0_ns: int, dur_ns: int, tid: Optional[int] = None, **attrs
) -> None:
    """Record an already-measured duration span — for callers that time a
    region manually and only know its extent after the fact (the
    consumer-side ``batch`` wait, which must not mark a terminal
    StopIteration as a failed span). ``tid`` places the span on a
    synthetic lane (serving's per-request tracks) instead of the calling
    thread's."""
    rec = RECORDER
    if rec.enabled:
        rec._record(name, t0_ns, dur_ns, attrs or None, "X", tid=tid)


def record_instant(
    name: str, t0_ns: int, tid: Optional[int] = None, **attrs
) -> None:
    """Record a point event at an explicit timestamp (``instant`` stamps
    now) — for shed/expiry markers that must land on the same clock and
    lane as the request spans around them."""
    rec = RECORDER
    if rec.enabled:
        rec._record(name, t0_ns, 0, attrs or None, "i", tid=tid)


def enable() -> SpanRecorder:
    RECORDER.enabled = True
    return RECORDER


def disable() -> None:
    RECORDER.enabled = False


def merge_chrome_traces(out_path: str, in_paths: Iterable[str]) -> Dict[str, Any]:
    """Fuse K per-process Chrome trace files (``save_chrome_trace``
    output, or any trace-event JSON object) into ONE Perfetto timeline
    with one labeled track per process, written atomically to
    ``out_path`` and returned.

    Processes are distinguished by pid, which is only unique per host:
    two files whose events share a pid but whose ``traceContext`` names a
    different host/root-span are given a fresh pid so their tracks never
    interleave. Files missing a ``process_name`` metadata record (traces
    from older recorders, hand-built files) get one synthesized from
    their context label or filename — every pid in the merged timeline
    renders as a named track. Unreadable/malformed inputs raise
    (ValueError/OSError): a silently dropped process would make the fused
    timeline lie."""
    files = []
    for path in in_paths:
        with open(path, "rb") as fh:
            try:
                obj = json.load(fh)
            except ValueError as e:
                raise ValueError(f"{path}: not valid JSON: {e}") from None
        if not isinstance(obj, dict) or not isinstance(
            obj.get("traceEvents"), list
        ):
            raise ValueError(f"{path}: not a Chrome trace-event JSON object")
        files.append((path, obj))
    events: List[Dict[str, Any]] = []
    contexts: List[Dict[str, Any]] = []
    owner: Dict[int, tuple] = {}  # output pid -> identity that holds it
    max_pid = 0
    for _, obj in files:
        for ev in obj["traceEvents"]:
            if isinstance(ev.get("pid"), int):
                max_pid = max(max_pid, ev["pid"])
    for idx, (path, obj) in enumerate(files):
        ctx = obj.get("traceContext")
        if not isinstance(ctx, dict):
            ctx = None
        if ctx is not None:
            contexts.append(ctx)
        # identity: same host + same root span = same process (a pid alone
        # collides across hosts); context-less files are their own identity
        ident_base = (
            (ctx.get("host"), ctx.get("pid"), ctx.get("span_id"))
            if ctx is not None
            else (os.path.basename(path), idx)
        )
        named = {
            ev.get("pid", 0)
            for ev in obj["traceEvents"]
            if ev.get("ph") == "M" and ev.get("name") == "process_name"
        }
        remap: Dict[int, int] = {}
        file_events: List[Dict[str, Any]] = []
        for ev in obj["traceEvents"]:
            pid = ev.get("pid", 0)
            out_pid = remap.get(pid)
            if out_pid is None:
                ident = ident_base + (pid,)
                out_pid = pid
                if owner.get(out_pid, ident) != ident:
                    max_pid += 1
                    out_pid = max_pid
                owner[out_pid] = ident
                remap[pid] = out_pid
                if pid not in named:
                    label = (
                        f"{ctx.get('role', 'proc')}@{ctx.get('host', '?')}:{pid}"
                        if ctx is not None
                        else os.path.basename(path)
                    )
                    events.append(
                        {
                            "name": "process_name",
                            "ph": "M",
                            "pid": out_pid,
                            "tid": 0,
                            "args": {"name": label},
                        }
                    )
            if out_pid != pid:
                ev = dict(ev, pid=out_pid)
            file_events.append(ev)
        events.extend(file_events)
    merged: Dict[str, Any] = {"traceEvents": events, "displayTimeUnit": "ms"}
    if contexts:
        merged["traceContexts"] = contexts
    atomic_write_bytes(out_path, json.dumps(merged).encode("utf-8"))
    return merged


# ---------------------------------------------------------------------------
# Bound-ness verdict
# ---------------------------------------------------------------------------

#: Gauge the consumer-side iterator maintains: EMA of the prefetch queue's
#: fill fraction sampled at each batch get (see io/dataset.py).
OCCUPANCY_GAUGE = "prefetch.occupancy"


def boundness_verdict(occupancy: Optional[float]) -> str:
    """Producer/consumer verdict from a queue fill fraction in [0, 1].

    ≥ 0.66: the queue is mostly full — the producer (decode) keeps ahead,
    so the CONSUMER is the bottleneck (``consumer_bound``; for a training
    loop this is the goal state: the device never waits on input).
    ≤ 0.33: mostly empty — the consumer drains faster than decode refills
    (``producer_bound``: speed up the input pipeline — more workers,
    cache, faster store). Between: ``balanced``. None: ``unknown`` (no
    samples yet)."""
    if occupancy is None:
        return "unknown"
    if occupancy >= 0.66:
        return "consumer_bound"
    if occupancy <= 0.33:
        return "producer_bound"
    return "balanced"


def verdict_from_metrics(metrics=None, gauge: str = OCCUPANCY_GAUGE) -> str:
    """The verdict for a metrics registry's occupancy gauge (the process
    default registry when ``metrics`` is None)."""
    if metrics is None:
        from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
    return boundness_verdict(metrics.gauge_value(gauge))


# ---------------------------------------------------------------------------
# Training verdict (the trainer-side twin of the bound-ness verdict)
# ---------------------------------------------------------------------------

#: The step-phase decomposition the training harness records
#: (examples/_harness.py StepPhases): disjoint wall-clock partitions of one
#: train step. Stage names are ``train.<phase>``; windowed phase shares are
#: published as ``train.share.<phase>`` gauges so the spool/doctor can read
#: a trainer's recent regime, not its lifetime average.
TRAIN_PHASES = ("data_wait", "h2d", "compute", "ckpt")
TRAIN_STAGE_PREFIX = "train."
TRAIN_SHARE_PREFIX = "train.share."

#: Verdict thresholds: a step spending >= this fraction on checkpointing
#: is ckpt_bound; >= this fraction on input (data_wait + h2d) is
#: input_bound (the tf.data-style diagnosis that drives elastic scaling —
#: an input_bound trainer wants more decode capacity, a compute_bound one
#: is the goal state).
TRAIN_CKPT_BOUND_SHARE = 0.25
TRAIN_INPUT_BOUND_SHARE = 0.5


def training_verdict(shares: Optional[Dict[str, float]]) -> str:
    """``input_bound`` / ``compute_bound`` / ``ckpt_bound`` / ``unknown``
    from a step-phase share mapping (keys = TRAIN_PHASES entries, values
    fractions of step wall time; missing phases read as 0).

    Checkpointing is checked first: a trainer drowning in ckpt writes is
    ckpt_bound even when its input pipeline is also slow — the fix (async
    or less frequent checkpoints) is different from "add decode workers",
    so the louder-signal phase wins. ``unknown`` when no shares exist."""
    if not shares or sum(shares.values()) <= 0:
        return "unknown"
    if shares.get("ckpt", 0.0) >= TRAIN_CKPT_BOUND_SHARE:
        return "ckpt_bound"
    input_share = shares.get("data_wait", 0.0) + shares.get("h2d", 0.0)
    if input_share >= TRAIN_INPUT_BOUND_SHARE:
        return "input_bound"
    return "compute_bound"


#: Queue depth (waiting requests) at or above this fraction of the
#: serving tier's admission bound reads as queue pressure — requests are
#: arriving faster than slots free, so the p99 miss is an ADMISSION
#: problem (shed more / add a replica), not a model-speed problem.
SERVE_QUEUE_BOUND_FILL = 0.5


def serving_verdict(
    p99_ms: Optional[float],
    queue_depth: Optional[float],
    slo_p99_ms: float,
    max_queue: int = 16,
) -> str:
    """Latency-SLO verdict for the serving tier (the inference-side twin
    of the bound-ness verdict): ``meeting_slo`` when per-request p99 is
    within ``slo_p99_ms``; on a miss, ``queue_bound`` when the waiting
    queue sits at ≥ ``SERVE_QUEUE_BOUND_FILL`` of the admission bound
    (latency is queueing delay — shed harder or scale out) else
    ``compute_bound`` (the compiled step itself is too slow for the SLO —
    a smaller model/bigger mesh problem no replica count fixes).
    ``unknown`` when no requests have completed yet."""
    if p99_ms is None:
        return "unknown"
    if p99_ms <= slo_p99_ms:
        return "meeting_slo"
    depth = 0.0 if queue_depth is None else float(queue_depth)
    if depth >= SERVE_QUEUE_BOUND_FILL * max(1, int(max_queue)):
        return "queue_bound"
    return "compute_bound"


class OccupancyEma:
    """Shared smoothing for the bound-ness occupancy gauges: one EMA
    (alpha 0.2 — the verdict reflects the recent regime, not the epoch's
    warmup) feeding one named gauge. Used by the consumer iterator
    (``prefetch.occupancy``) and the write slab pipeline
    (``write.occupancy``), so both verdicts read identically-smoothed
    signals."""

    __slots__ = ("gauge", "alpha", "value")

    def __init__(self, gauge: str, alpha: float = 0.2):
        self.gauge = gauge
        self.alpha = alpha
        self.value: Optional[float] = None

    def update(self, fill: float, metrics=None) -> float:
        v = self.value
        self.value = (
            fill if v is None else (1.0 - self.alpha) * v + self.alpha * fill
        )
        if metrics is None:
            from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
        metrics.gauge(self.gauge, self.value)
        return self.value


#: Histogram families that hold DIMENSIONLESS values (fractions/ratios —
#: the in-jit model diagnostics the training harness folds each step),
#: not seconds: every ms-renderer must skip them, or a dropped-token
#: fraction of 0.02 would print as "20ms of latency" on the fleet page.
DIMENSIONLESS_HIST_PREFIXES = ("moe.", "pipeline.")


def is_latency_hist(name: str) -> bool:
    return not name.startswith(DIMENSIONLESS_HIST_PREFIXES)


def quantiles_ms(source: Dict[str, Dict[str, float]]) -> Dict[str, Dict[str, float]]:
    """Convert a ``Metrics.quantiles()`` mapping — or any mapping whose
    entries carry ``p50_s``/``p90_s``/``p99_s`` (``snapshot()`` stage
    entries qualify) — into the shared milliseconds shape the pulse,
    bench, and doctor lines all emit, so their field sets cannot drift
    apart. Entries without quantiles are skipped, as are the
    DIMENSIONLESS diagnostic histograms (their values are fractions;
    rendering them as milliseconds would lie)."""
    out: Dict[str, Dict[str, float]] = {}
    for name, q in sorted(source.items()):
        if not q or "p50_s" not in q or not is_latency_hist(name):
            continue
        entry = {
            "p50_ms": round(q["p50_s"] * 1e3, 3),
            "p90_ms": round(q["p90_s"] * 1e3, 3),
            "p99_ms": round(q["p99_s"] * 1e3, 3),
        }
        if "count" in q:
            entry["count"] = q["count"]
        elif "hist_count" in q:
            entry["count"] = int(q["hist_count"])
        out[name] = entry
    return out


# ---------------------------------------------------------------------------
# Telemetry pulse
# ---------------------------------------------------------------------------


class Pulse:
    """Periodic one-line-JSON telemetry reporter.

    Every ``interval_s`` the pulse thread emits one machine-parseable dict
    through ``emit`` (default: a ``tfrecord.pulse {json}`` INFO line on the
    package logger — the same fleet-log convention as
    ``log_salvage_event``). Stage throughputs are PER-INTERVAL deltas
    (records/bytes produced this interval over the interval wall time), so
    a stall shows up as the pulse going to zero, not as a slowly decaying
    lifetime average; counters, gauges, and histogram quantiles are
    cumulative snapshots. ``tick()`` is public so tests and the doctor can
    force a pulse without waiting out the interval."""

    def __init__(
        self,
        interval_s: float,
        metrics=None,
        emit: Optional[Callable[[Dict[str, Any]], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if metrics is None:
            from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
        self.interval_s = interval_s
        self.metrics = metrics
        self.emit = emit if emit is not None else _log_pulse
        self._clock = clock
        self._prev_totals: Dict[str, Tuple[int, int, int, float]] = {}
        self._prev_t = clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._observers: List[Callable[[Dict[str, Any]], Optional[Dict]]] = []

    def add_observer(
        self, fn: Callable[[Dict[str, Any]], Optional[Dict]]
    ) -> "Pulse":
        """Register a per-tick observer. Each tick, after the payload is
        computed and before it is emitted, every observer is called with
        the payload; a returned dict is merged into the emitted line. The
        autotune controller runs this way (its decisions land in the same
        pulse line that carries the interval they were made from).
        Observer exceptions are swallowed — telemetry (and tuning riding
        on it) must never take the pipeline down."""
        self._observers.append(fn)
        return self

    def start(self) -> "Pulse":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tfr-pulse"
            )
            self._thread.start()
        return self

    def stop(self, final: bool = True) -> None:
        """Stop the thread; ``final`` emits one last pulse covering the
        tail interval so short epochs still leave a line behind.
        Idempotent: a second stop (iterator close + GC finalizer) does
        nothing."""
        already = self._stop.is_set()
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
        if final and not already:
            try:
                self.tick()
            except Exception:  # graftlint: swallow(final tail tick is best-effort at stop)
                pass

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # graftlint: swallow(telemetry must never take the pipeline down)
                # telemetry must never take the pipeline down
                pass

    def tick(self) -> Dict[str, Any]:
        """Compute and emit one pulse line; returns the emitted dict."""
        now = self._clock()
        dt = max(now - self._prev_t, 1e-9)
        self._prev_t = now
        totals = self.metrics.raw_totals()
        stages: Dict[str, Dict[str, float]] = {}
        counters: Dict[str, int] = {}
        for name, (records, nbytes, batches, seconds) in sorted(totals.items()):
            prev = self._prev_totals.get(name, (0, 0, 0, 0.0))
            d_rec = records - prev[0]
            d_bytes = nbytes - prev[1]
            if seconds == 0.0 and nbytes == 0:
                # a pure count()-style event counter (read.retries,
                # cache.hits, *.errors): cumulative total + interval delta
                counters[name] = records
                if d_rec:
                    counters[name + ".delta"] = d_rec
                continue
            stages[name] = {
                "records_per_sec": round(d_rec / dt, 1),
                "bytes_per_sec": round(d_bytes / dt, 1),
                "records": records,
            }
        self._prev_totals = totals
        gauges = self.metrics.gauges()
        quantiles = quantiles_ms(self.metrics.quantiles())
        ctx = current_context()
        payload = {
            "event": "pulse",
            "ts": round(time.time(), 3),
            "interval_s": round(dt, 3),
            # process identity: in a fleet (every process pulsing into one
            # log stream) a line is unattributable without host/pid/role,
            # and trace_id correlates the line with the merged timeline
            "proc": {
                "host": ctx.host,
                "pid": ctx.pid,
                "role": ctx.role,
                "trace_id": ctx.trace_id,
            },
            "stages": stages,
            "counters": counters,
            "gauges": {k: round(v, 4) for k, v in sorted(gauges.items())},
            "quantiles": quantiles,
            "verdict": boundness_verdict(gauges.get(OCCUPANCY_GAUGE)),
        }
        for fn in list(self._observers):
            try:
                extra = fn(payload)
                if extra:
                    payload.update(extra)
            except Exception:
                # observers must never take the pipeline down — but a
                # crashing controller silently freezing the knobs must
                # not be invisible either: the error counter lands in
                # this very pulse's counters on the NEXT tick
                try:
                    self.metrics.count("pulse.observer_errors")
                except Exception:  # graftlint: swallow(the observer_errors counter itself failed)
                    pass
        self.emit(payload)
        return payload


def _log_pulse(payload: Dict[str, Any]) -> None:
    from tpu_tfrecord.metrics import logger

    logger.info("tfrecord.pulse %s", json.dumps(payload, sort_keys=True))


# ---------------------------------------------------------------------------
# Prometheus text endpoint (stdlib HTTP only)
# ---------------------------------------------------------------------------


def escape_label_value(v: Any) -> str:
    """Prometheus label-value escaping: a value containing a quote,
    backslash, or newline must not break the exposition format."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def append_family(
    lines: List[str], fam: str, ftype: str, samples: List[str]
) -> None:
    """Append one metric family to an exposition page. The format requires
    every sample of one family to form a single contiguous block under its
    # TYPE line — interleaving families per stage makes strict parsers
    (promtool, OpenMetrics scrapes) reject the page as duplicate families,
    so both the process page and the fleet's federated page build each
    family's samples in full before appending through here."""
    if samples:
        lines.append(f"# TYPE {fam} {ftype}")
        lines.extend(samples)


def summary_family_lines(
    fam: str, labeled_quantiles: Iterable[Tuple[str, Dict[str, float]]]
) -> List[str]:
    """Samples for a p50/p90/p99 summary family from ``quantiles()``-shaped
    dicts: per entry, one ``fam{<labels>,quantile="q"} v`` line per
    quantile plus the ``fam_count{<labels>}`` line."""
    samples: List[str] = []
    for label, q in labeled_quantiles:
        if not q:
            continue
        for key, quant in (("p50_s", "0.5"), ("p90_s", "0.9"), ("p99_s", "0.99")):
            samples.append(f'{fam}{{{label},quantile="{quant}"}} {q[key]:.9f}')
        samples.append(f'{fam}_count{{{label}}} {q["count"]}')
    return samples


def prometheus_text(metrics=None) -> str:
    """The registry in Prometheus text exposition format: stage totals as
    counters, gauges as gauges, histogram quantiles as a summary-style
    family. Stage/gauge names ride in label values (where dots are legal),
    so the metric-family names stay fixed and dashboards survive new
    stages."""
    if metrics is None:
        from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
    totals = sorted(metrics.raw_totals().items())
    lines: List[str] = []

    def family(fam: str, ftype: str, samples: List[str]) -> None:
        append_family(lines, fam, ftype, samples)

    family(
        "tfrecord_stage_records_total",
        "counter",
        [
            f'tfrecord_stage_records_total{{stage="{n}"}} {r}'
            for n, (r, _b, _bt, _s) in totals
        ],
    )
    family(
        "tfrecord_stage_bytes_total",
        "counter",
        [
            f'tfrecord_stage_bytes_total{{stage="{n}"}} {b}'
            for n, (_r, b, _bt, _s) in totals
            if b
        ],
    )
    family(
        "tfrecord_stage_seconds_total",
        "counter",
        [
            f'tfrecord_stage_seconds_total{{stage="{n}"}} {s:.6f}'
            for n, (_r, _b, _bt, s) in totals
            if s
        ],
    )
    family(
        "tfrecord_gauge",
        "gauge",
        [
            f'tfrecord_gauge{{name="{name}"}} {value:.6g}'
            for name, value in sorted(metrics.gauges().items())
        ],
    )
    family(
        "tfrecord_latency_seconds",
        "summary",
        summary_family_lines(
            "tfrecord_latency_seconds",
            (
                (f'stage="{name}"', q)
                for name, q in sorted(metrics.quantiles().items())
            ),
        ),
    )
    # Exemplars as a dedicated gauge family (value = the exemplared
    # observation, seconds) instead of OpenMetrics `# {...}` suffixes —
    # the pinned text-format 0.0.4 parse of this page would reject the
    # suffix syntax. `le` is the bucket's upper bound, so a tail sample
    # here is clickable back to its trace/span ids.
    family(
        "tfrecord_latency_exemplar_seconds",
        "gauge",
        [
            "tfrecord_latency_exemplar_seconds{"
            f'stage="{escape_label_value(name)}",'
            f'le="{Histogram.bucket_le(int(idx)):.6g}",'
            f'trace_id="{escape_label_value(t)}",'
            f'span_id="{escape_label_value(s)}"'
            "} " + f"{v:.6g}"
            for name, state in sorted(metrics.hist_states().items())
            if is_latency_hist(name)
            for idx, (t, s, v) in sorted(
                (state.get("exemplars") or {}).items(), key=lambda kv: int(kv[0])
            )
        ],
    )
    return "\n".join(lines) + "\n"


_EXPORTERS: Dict[int, Any] = {}
_EXPORTERS_LOCK = threading.Lock()


def ensure_exporter(port: int, metrics=None):
    """Start (or return the already-running) Prometheus text endpoint on
    ``port`` — process-wide, idempotent per port, daemon-threaded. ``port``
    0 binds an ephemeral port; the bound address is logged at startup and
    queryable via ``exporter_address(port)`` (keyed by the REQUESTED port,
    as is ``shutdown_exporter`` — pass 0 back, not the ephemeral number).
    Serves ``/metrics`` (and ``/`` as an alias); anything else 404s.
    Stdlib ``http.server`` only — no new dependencies. A port that cannot
    be bound (already taken by another process) logs a warning and returns
    None — telemetry must never take the pipeline down."""
    if metrics is None:
        from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813

    reg = metrics
    return serve_text_endpoint(port, lambda: prometheus_text(reg))


def serve_text_endpoint(
    port: int, render: Callable[[], str], kind: str = "process"
):
    """The stdlib-HTTP plumbing under ``ensure_exporter``, parameterized
    on the page renderer so other registries (the fleet aggregator's
    federated page, tpu_tfrecord.fleet) serve through the same idempotent
    per-port server table without duplicating it. Same contract:
    idempotent per requested port; unbindable port warns and returns
    None. A port already serving a DIFFERENT page kind (e.g. a
    ``telemetry_port=0`` process exporter claimed key 0 and a fleet
    aggregator now asks for 0) also warns and returns None — returning
    the existing server would let the caller report success while every
    scrape silently gets the wrong page."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from tpu_tfrecord.metrics import logger

    with _EXPORTERS_LOCK:
        server = _EXPORTERS.get(port)
        if server is not None:
            served = getattr(server, "_tfr_kind", "process")
            if served != kind:
                logger.warning(
                    "tfrecord.telemetry endpoint for requested port %d "
                    "already serves the %r page; NOT replacing it with the "
                    "requested %r page — use a different port",
                    port, served, kind,
                )
                return None  # callers must see the failure, not a server
            return server

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # quiet: telemetry, not access logs
                return

        try:
            server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        except OSError as e:
            # a taken port (two processes sharing one config) must not
            # take the pipeline down — telemetry is never load-bearing
            logger.warning(
                "tfrecord.telemetry prometheus endpoint on port %d "
                "unavailable (%s); continuing without it", port, e,
            )
            return None
        server.daemon_threads = True
        server._tfr_kind = kind
        threading.Thread(
            target=server.serve_forever, daemon=True, name="tfr-prometheus"
        ).start()
        _EXPORTERS[port] = server
        host, bound = server.server_address[:2]
        logger.info(
            "tfrecord.telemetry prometheus endpoint on http://%s:%d/metrics",
            host, bound,
        )
        return server


def exporter_address(port: int) -> Optional[Tuple[str, int]]:
    """(host, bound_port) of the exporter started for REQUESTED ``port``
    (the public way to learn which ephemeral port ``telemetry_port=0``
    actually bound), or None when none is running."""
    with _EXPORTERS_LOCK:
        server = _EXPORTERS.get(port)
    return server.server_address[:2] if server is not None else None


def shutdown_exporter(port: int) -> None:
    """Stop the exporter started for REQUESTED ``port`` (tests; production
    leaves it up). For an ephemeral exporter pass 0 — the key is the port
    you asked for, not the one the OS picked."""
    with _EXPORTERS_LOCK:
        server = _EXPORTERS.pop(port, None)
    if server is not None:
        server.shutdown()
        server.server_close()
