"""Pipeline flight recorder: span tracing, latency histograms, telemetry
pulse, and the producer/consumer bound-ness verdict.

The reference has no observability of its own (SURVEY.md §5: tracing ABSENT
— it rides on Spark's UI), and `Metrics` only aggregates per-stage TOTALS:
after an epoch you know decode took N seconds, but not the latency
distribution, which shard was the straggler, or whether the pipeline was
producer- or consumer-bound at any moment. tf.data's auto-tuning and the
tf.data service rest on exactly this kind of per-op timing instrumentation
(PAPERS.md) — a slow epoch should be explainable without attaching a
profiler. Four pieces:

- **Span tracing** (``SpanRecorder``): a thread-safe, bounded ring buffer
  of (name, begin, duration, thread, attrs) records, written through the
  ``span(name, **attrs)`` context manager and ``instant(name, **attrs)``
  point events. Opt-in via ``TFRecordOptions(trace="on")`` — when off, the
  module-level ``span()``/``instant()`` return a shared no-op without
  taking any lock (one attribute read on the hot path). Exportable as
  Chrome trace-event JSON (``to_chrome_trace``/``save_chrome_trace``) —
  loadable in Perfetto / chrome://tracing. Spans are mirrored onto the
  jax-profiler timeline through the existing ``tracing.trace`` annotations
  every instrumented site already holds, so xprof captures show the same
  regions.

- **Latency histograms** (``Histogram``): log-bucketed (~19% geometric
  buckets → quantile relative error ≤ ~10%), folded into ``Metrics`` via
  ``Metrics.observe``/the ``timed`` context manager, so every timed stage
  (shard open, slab read, chunk decode, cache serve, write/commit) grows a
  p50/p90/p99 next to its totals and stragglers stop hiding inside means.

- **Telemetry pulse** (``Pulse``): a background reporter emitting one
  machine-parseable JSON line per interval — per-interval stage
  throughputs, cumulative counters, histogram quantiles, gauges (prefetch
  queue depth, in-flight decode workers, backpressure occupancy), and the
  bound-ness verdict. Opt-in via ``TFRecordOptions(pulse_interval_s=...)``;
  an optional stdlib-HTTP Prometheus text endpoint
  (``TFRecordOptions(telemetry_port=...)`` / ``ensure_exporter``) serves
  the same registry for scraping. Pulse ticks are also the pipeline's
  ACTUATION points: registered observers (``add_observer``) see each
  payload before it is emitted and may merge fields into the line — the
  closed-loop autotuner (tpu_tfrecord.autotune) runs this way, so every
  knob decision lands in the same trace as the interval it was made from.

- **Bound-ness verdict** (``boundness_verdict``): computed from the
  prefetch queue's average fill fraction, sampled by the consumer. A queue
  that is nearly always FULL means decode keeps ahead of the consumer —
  the pipeline is consumer-bound (the device/training step is the
  bottleneck; the BASELINE.md goal state). Nearly always EMPTY means the
  consumer drains batches faster than decode produces them —
  producer-bound (speed up the input pipeline).

The offline complement is ``tools/tfrecord_doctor.py report DATA_DIR``:
run N batches with tracing on and print the stage breakdown, slowest
shards, straggler ratio, and the verdict.

This module deliberately imports nothing from the rest of the package at
module level (stdlib only; the default-registry lookups import
``metrics`` lazily), so every layer — metrics, io, cache, stall — can
import it without cycles.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "Histogram",
    "SpanRecorder",
    "Pulse",
    "RECORDER",
    "span",
    "instant",
    "enable",
    "disable",
    "boundness_verdict",
    "verdict_from_metrics",
    "OccupancyEma",
    "quantiles_ms",
    "prometheus_text",
    "ensure_exporter",
    "exporter_address",
    "shutdown_exporter",
]


# ---------------------------------------------------------------------------
# Latency histograms
# ---------------------------------------------------------------------------


class Histogram:
    """Log-bucketed latency histogram with quantile estimation.

    Buckets grow geometrically by ``2**0.25`` (~19% per bucket) from a
    100 ns floor, spanning 100 ns .. ~1.9 h in 144 fixed buckets — so one
    histogram is a flat int list, O(1) to observe and cheap to snapshot.
    Quantiles interpolate at the log-midpoint of the selected bucket and
    clamp to the observed [min, max], bounding the relative error at
    ``sqrt(2**0.25) - 1`` ≈ 9.1% (pinned against a reference sort in
    tests/test_telemetry.py).

    NOT internally locked: the owner (``Metrics``) serializes access under
    its own lock so one observation costs one lock acquisition total.
    """

    _MIN = 1e-7  # 100 ns floor: anything faster is bucket 0
    _LOG2_GROWTH = 0.25  # buckets grow by 2**0.25 per step
    _NBUCKETS = 144  # 144 * 0.25 = 36 octaves above _MIN (~1.9 h)

    __slots__ = ("counts", "count", "total", "min", "max")

    def __init__(self) -> None:
        self.counts = [0] * self._NBUCKETS
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = 0.0

    def observe(self, value: float) -> None:
        if value <= self._MIN:
            idx = 0
        else:
            idx = min(
                self._NBUCKETS - 1,
                1 + int(math.log2(value / self._MIN) / self._LOG2_GROWTH),
            )
        self.counts[idx] += 1
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def quantile(self, q: float) -> Optional[float]:
        """Estimated value at quantile ``q`` in [0, 1] (None when empty)."""
        if self.count == 0:
            return None
        rank = q * self.count
        cum = 0
        for idx, c in enumerate(self.counts):
            cum += c
            if cum >= rank and c:
                if idx == 0:
                    est = self._MIN
                else:
                    # log-midpoint of the bucket [g**(idx-1), g**idx) * _MIN
                    est = self._MIN * 2 ** ((idx - 0.5) * self._LOG2_GROWTH)
                return min(max(est, self.min), self.max)
        return self.max

    def quantiles(self) -> Dict[str, float]:
        """The standard p50/p90/p99 snapshot (seconds), plus count/mean."""
        if self.count == 0:
            return {}
        return {
            "p50_s": self.quantile(0.50),
            "p90_s": self.quantile(0.90),
            "p99_s": self.quantile(0.99),
            "count": self.count,
            "mean_s": self.total / self.count,
        }


# ---------------------------------------------------------------------------
# Span tracing
# ---------------------------------------------------------------------------


class _NoopSpan:
    """The shared disabled-path context manager: no state, no lock."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def set(self, **attrs) -> None:
        return None


_NOOP = _NoopSpan()


class _SpanCtx:
    """One live span: records (name, begin, duration, tid, attrs) into its
    recorder on exit. An exception propagating through the span marks it
    ``failed=1`` — error latency stays attributed to its stage."""

    __slots__ = ("_rec", "name", "attrs", "_t0")

    def __init__(self, rec: "SpanRecorder", name: str, attrs: Optional[dict]):
        self._rec = rec
        self.name = name
        self.attrs = attrs

    def set(self, **attrs) -> None:
        """Attach attrs discovered mid-span (row counts, byte counts)."""
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur = time.perf_counter_ns() - self._t0
        attrs = self.attrs
        if exc_type is not None:
            attrs = dict(attrs or (), failed=1)
        self._rec._record(self.name, self._t0, dur, attrs, "X")
        return None


class SpanRecorder:
    """Thread-safe bounded ring buffer of span/instant records.

    ``capacity`` bounds memory for arbitrarily long epochs: the buffer
    keeps the most recent ``capacity`` records and counts what it dropped
    (``dropped``) — a flight recorder, not an archive. ``enabled`` is a
    plain attribute read on the hot path; when False, the module-level
    ``span()``/``instant()`` return the shared no-op without touching this
    object's lock (pinned by tests/test_telemetry.py).
    """

    def __init__(self, capacity: int = 65536, enabled: bool = False):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.enabled = enabled
        self._lock = threading.Lock()
        # ring storage: fixed-size list + running sequence number
        self._ring: List[Optional[tuple]] = [None] * capacity
        self._seq = 0
        self.dropped = 0

    # -- recording -----------------------------------------------------------

    def span(self, name: str, **attrs) -> "_SpanCtx | _NoopSpan":
        if not self.enabled:
            return _NOOP
        return _SpanCtx(self, name, attrs or None)

    def instant(self, name: str, **attrs) -> None:
        if not self.enabled:
            return
        self._record(name, time.perf_counter_ns(), 0, attrs or None, "i")

    def _record(
        self, name: str, t0_ns: int, dur_ns: int, attrs: Optional[dict], ph: str
    ) -> None:
        tid = threading.get_ident()
        with self._lock:
            seq = self._seq
            self._seq = seq + 1
            if seq >= self.capacity:
                self.dropped += 1
            self._ring[seq % self.capacity] = (name, t0_ns, dur_ns, tid, attrs, ph)

    # -- reading -------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return min(self._seq, self.capacity)

    def spans(self) -> List[tuple]:
        """Snapshot of retained records, oldest first:
        (name, t0_ns, dur_ns, tid, attrs, ph)."""
        with self._lock:
            seq = self._seq
            if seq <= self.capacity:
                return [r for r in self._ring[:seq]]
            start = seq % self.capacity
            return [
                r
                for r in (self._ring[start:] + self._ring[:start])
                if r is not None
            ]

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._seq = 0
            self.dropped = 0

    # -- export --------------------------------------------------------------

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The retained records as a Chrome trace-event JSON object
        (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU
        — the format Perfetto and chrome://tracing load). Durations are
        complete ("X") events; point events are instants ("i")."""
        pid = os.getpid()
        events = []
        for name, t0_ns, dur_ns, tid, attrs, ph in self.spans():
            ev: Dict[str, Any] = {
                "name": name,
                "cat": "tfrecord",
                "ph": ph,
                "ts": t0_ns / 1000.0,  # microseconds
                "pid": pid,
                "tid": tid,
            }
            if ph == "X":
                ev["dur"] = dur_ns / 1000.0
            else:
                ev["s"] = "t"  # thread-scoped instant
            if attrs:
                ev["args"] = attrs
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def save_chrome_trace(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_chrome_trace(), fh)


#: Process-global flight recorder — spans come from dataset iterators,
#: prefetch workers, writer pipeline threads, and the stall guard, so the
#: ring is shared (one timeline). ``TFRecordOptions(trace="on")`` enables it
#: at dataset/writer construction; it stays on until ``disable()``.
RECORDER = SpanRecorder()


def span(name: str, **attrs):
    """Record a duration span on the global recorder; a shared no-op (no
    lock, no allocation beyond the caller's kwargs) when tracing is off."""
    rec = RECORDER
    if not rec.enabled:
        return _NOOP
    return _SpanCtx(rec, name, attrs or None)


def instant(name: str, **attrs) -> None:
    """Record a point event (stall, hedge, retry, watchdog restart)."""
    rec = RECORDER
    if rec.enabled:
        rec._record(name, time.perf_counter_ns(), 0, attrs or None, "i")


def record_span(name: str, t0_ns: int, dur_ns: int, **attrs) -> None:
    """Record an already-measured duration span — for callers that time a
    region manually and only know its extent after the fact (the
    consumer-side ``batch`` wait, which must not mark a terminal
    StopIteration as a failed span)."""
    rec = RECORDER
    if rec.enabled:
        rec._record(name, t0_ns, dur_ns, attrs or None, "X")


def enable() -> SpanRecorder:
    RECORDER.enabled = True
    return RECORDER


def disable() -> None:
    RECORDER.enabled = False


# ---------------------------------------------------------------------------
# Bound-ness verdict
# ---------------------------------------------------------------------------

#: Gauge the consumer-side iterator maintains: EMA of the prefetch queue's
#: fill fraction sampled at each batch get (see io/dataset.py).
OCCUPANCY_GAUGE = "prefetch.occupancy"


def boundness_verdict(occupancy: Optional[float]) -> str:
    """Producer/consumer verdict from a queue fill fraction in [0, 1].

    ≥ 0.66: the queue is mostly full — the producer (decode) keeps ahead,
    so the CONSUMER is the bottleneck (``consumer_bound``; for a training
    loop this is the goal state: the device never waits on input).
    ≤ 0.33: mostly empty — the consumer drains faster than decode refills
    (``producer_bound``: speed up the input pipeline — more workers,
    cache, faster store). Between: ``balanced``. None: ``unknown`` (no
    samples yet)."""
    if occupancy is None:
        return "unknown"
    if occupancy >= 0.66:
        return "consumer_bound"
    if occupancy <= 0.33:
        return "producer_bound"
    return "balanced"


def verdict_from_metrics(metrics=None, gauge: str = OCCUPANCY_GAUGE) -> str:
    """The verdict for a metrics registry's occupancy gauge (the process
    default registry when ``metrics`` is None)."""
    if metrics is None:
        from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
    return boundness_verdict(metrics.gauge_value(gauge))


class OccupancyEma:
    """Shared smoothing for the bound-ness occupancy gauges: one EMA
    (alpha 0.2 — the verdict reflects the recent regime, not the epoch's
    warmup) feeding one named gauge. Used by the consumer iterator
    (``prefetch.occupancy``) and the write slab pipeline
    (``write.occupancy``), so both verdicts read identically-smoothed
    signals."""

    __slots__ = ("gauge", "alpha", "value")

    def __init__(self, gauge: str, alpha: float = 0.2):
        self.gauge = gauge
        self.alpha = alpha
        self.value: Optional[float] = None

    def update(self, fill: float, metrics=None) -> float:
        v = self.value
        self.value = (
            fill if v is None else (1.0 - self.alpha) * v + self.alpha * fill
        )
        if metrics is None:
            from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
        metrics.gauge(self.gauge, self.value)
        return self.value


def quantiles_ms(source: Dict[str, Dict[str, float]]) -> Dict[str, Dict[str, float]]:
    """Convert a ``Metrics.quantiles()`` mapping — or any mapping whose
    entries carry ``p50_s``/``p90_s``/``p99_s`` (``snapshot()`` stage
    entries qualify) — into the shared milliseconds shape the pulse,
    bench, and doctor lines all emit, so their field sets cannot drift
    apart. Entries without quantiles are skipped."""
    out: Dict[str, Dict[str, float]] = {}
    for name, q in sorted(source.items()):
        if not q or "p50_s" not in q:
            continue
        entry = {
            "p50_ms": round(q["p50_s"] * 1e3, 3),
            "p90_ms": round(q["p90_s"] * 1e3, 3),
            "p99_ms": round(q["p99_s"] * 1e3, 3),
        }
        if "count" in q:
            entry["count"] = q["count"]
        elif "hist_count" in q:
            entry["count"] = int(q["hist_count"])
        out[name] = entry
    return out


# ---------------------------------------------------------------------------
# Telemetry pulse
# ---------------------------------------------------------------------------


class Pulse:
    """Periodic one-line-JSON telemetry reporter.

    Every ``interval_s`` the pulse thread emits one machine-parseable dict
    through ``emit`` (default: a ``tfrecord.pulse {json}`` INFO line on the
    package logger — the same fleet-log convention as
    ``log_salvage_event``). Stage throughputs are PER-INTERVAL deltas
    (records/bytes produced this interval over the interval wall time), so
    a stall shows up as the pulse going to zero, not as a slowly decaying
    lifetime average; counters, gauges, and histogram quantiles are
    cumulative snapshots. ``tick()`` is public so tests and the doctor can
    force a pulse without waiting out the interval."""

    def __init__(
        self,
        interval_s: float,
        metrics=None,
        emit: Optional[Callable[[Dict[str, Any]], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if metrics is None:
            from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
        self.interval_s = interval_s
        self.metrics = metrics
        self.emit = emit if emit is not None else _log_pulse
        self._clock = clock
        self._prev_totals: Dict[str, Tuple[int, int, int, float]] = {}
        self._prev_t = clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._observers: List[Callable[[Dict[str, Any]], Optional[Dict]]] = []

    def add_observer(
        self, fn: Callable[[Dict[str, Any]], Optional[Dict]]
    ) -> "Pulse":
        """Register a per-tick observer. Each tick, after the payload is
        computed and before it is emitted, every observer is called with
        the payload; a returned dict is merged into the emitted line. The
        autotune controller runs this way (its decisions land in the same
        pulse line that carries the interval they were made from).
        Observer exceptions are swallowed — telemetry (and tuning riding
        on it) must never take the pipeline down."""
        self._observers.append(fn)
        return self

    def start(self) -> "Pulse":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tfr-pulse"
            )
            self._thread.start()
        return self

    def stop(self, final: bool = True) -> None:
        """Stop the thread; ``final`` emits one last pulse covering the
        tail interval so short epochs still leave a line behind.
        Idempotent: a second stop (iterator close + GC finalizer) does
        nothing."""
        already = self._stop.is_set()
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
        if final and not already:
            try:
                self.tick()
            except Exception:
                pass

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # telemetry must never take the pipeline down
                pass

    def tick(self) -> Dict[str, Any]:
        """Compute and emit one pulse line; returns the emitted dict."""
        now = self._clock()
        dt = max(now - self._prev_t, 1e-9)
        self._prev_t = now
        totals = self.metrics.raw_totals()
        stages: Dict[str, Dict[str, float]] = {}
        counters: Dict[str, int] = {}
        for name, (records, nbytes, batches, seconds) in sorted(totals.items()):
            prev = self._prev_totals.get(name, (0, 0, 0, 0.0))
            d_rec = records - prev[0]
            d_bytes = nbytes - prev[1]
            if seconds == 0.0 and nbytes == 0:
                # a pure count()-style event counter (read.retries,
                # cache.hits, *.errors): cumulative total + interval delta
                counters[name] = records
                if d_rec:
                    counters[name + ".delta"] = d_rec
                continue
            stages[name] = {
                "records_per_sec": round(d_rec / dt, 1),
                "bytes_per_sec": round(d_bytes / dt, 1),
                "records": records,
            }
        self._prev_totals = totals
        gauges = self.metrics.gauges()
        quantiles = quantiles_ms(self.metrics.quantiles())
        payload = {
            "event": "pulse",
            "ts": round(time.time(), 3),
            "interval_s": round(dt, 3),
            "stages": stages,
            "counters": counters,
            "gauges": {k: round(v, 4) for k, v in sorted(gauges.items())},
            "quantiles": quantiles,
            "verdict": boundness_verdict(gauges.get(OCCUPANCY_GAUGE)),
        }
        for fn in list(self._observers):
            try:
                extra = fn(payload)
                if extra:
                    payload.update(extra)
            except Exception:
                # observers must never take the pipeline down — but a
                # crashing controller silently freezing the knobs must
                # not be invisible either: the error counter lands in
                # this very pulse's counters on the NEXT tick
                try:
                    self.metrics.count("pulse.observer_errors")
                except Exception:
                    pass
        self.emit(payload)
        return payload


def _log_pulse(payload: Dict[str, Any]) -> None:
    from tpu_tfrecord.metrics import logger

    logger.info("tfrecord.pulse %s", json.dumps(payload, sort_keys=True))


# ---------------------------------------------------------------------------
# Prometheus text endpoint (stdlib HTTP only)
# ---------------------------------------------------------------------------


def prometheus_text(metrics=None) -> str:
    """The registry in Prometheus text exposition format: stage totals as
    counters, gauges as gauges, histogram quantiles as a summary-style
    family. Stage/gauge names ride in label values (where dots are legal),
    so the metric-family names stay fixed and dashboards survive new
    stages."""
    if metrics is None:
        from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
    totals = sorted(metrics.raw_totals().items())
    lines: List[str] = []

    def family(fam: str, ftype: str, samples: List[str]) -> None:
        # the exposition format requires every sample of one metric family
        # to form a single contiguous block under its # TYPE line —
        # interleaving families per stage makes strict parsers (promtool,
        # OpenMetrics scrapes) reject the page as duplicate families
        if samples:
            lines.append(f"# TYPE {fam} {ftype}")
            lines.extend(samples)

    family(
        "tfrecord_stage_records_total",
        "counter",
        [
            f'tfrecord_stage_records_total{{stage="{n}"}} {r}'
            for n, (r, _b, _bt, _s) in totals
        ],
    )
    family(
        "tfrecord_stage_bytes_total",
        "counter",
        [
            f'tfrecord_stage_bytes_total{{stage="{n}"}} {b}'
            for n, (_r, b, _bt, _s) in totals
            if b
        ],
    )
    family(
        "tfrecord_stage_seconds_total",
        "counter",
        [
            f'tfrecord_stage_seconds_total{{stage="{n}"}} {s:.6f}'
            for n, (_r, _b, _bt, s) in totals
            if s
        ],
    )
    family(
        "tfrecord_gauge",
        "gauge",
        [
            f'tfrecord_gauge{{name="{name}"}} {value:.6g}'
            for name, value in sorted(metrics.gauges().items())
        ],
    )
    latency: List[str] = []
    for name, q in sorted(metrics.quantiles().items()):
        if not q:
            continue
        for key, quant in (("p50_s", "0.5"), ("p90_s", "0.9"), ("p99_s", "0.99")):
            latency.append(
                f'tfrecord_latency_seconds{{stage="{name}",'
                f'quantile="{quant}"}} {q[key]:.9f}'
            )
        latency.append(
            f'tfrecord_latency_seconds_count{{stage="{name}"}} {q["count"]}'
        )
    family("tfrecord_latency_seconds", "summary", latency)
    return "\n".join(lines) + "\n"


_EXPORTERS: Dict[int, Any] = {}
_EXPORTERS_LOCK = threading.Lock()


def ensure_exporter(port: int, metrics=None):
    """Start (or return the already-running) Prometheus text endpoint on
    ``port`` — process-wide, idempotent per port, daemon-threaded. ``port``
    0 binds an ephemeral port; the bound address is logged at startup and
    queryable via ``exporter_address(port)`` (keyed by the REQUESTED port,
    as is ``shutdown_exporter`` — pass 0 back, not the ephemeral number).
    Serves ``/metrics`` (and ``/`` as an alias); anything else 404s.
    Stdlib ``http.server`` only — no new dependencies. A port that cannot
    be bound (already taken by another process) logs a warning and returns
    None — telemetry must never take the pipeline down."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if metrics is None:
        from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813

    from tpu_tfrecord.metrics import logger

    with _EXPORTERS_LOCK:
        server = _EXPORTERS.get(port)
        if server is not None:
            return server

        reg = metrics

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = prometheus_text(reg).encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # quiet: telemetry, not access logs
                return

        try:
            server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        except OSError as e:
            # a taken port (two processes sharing one config) must not
            # take the pipeline down — telemetry is never load-bearing
            logger.warning(
                "tfrecord.telemetry prometheus endpoint on port %d "
                "unavailable (%s); continuing without it", port, e,
            )
            return None
        server.daemon_threads = True
        threading.Thread(
            target=server.serve_forever, daemon=True, name="tfr-prometheus"
        ).start()
        _EXPORTERS[port] = server
        host, bound = server.server_address[:2]
        logger.info(
            "tfrecord.telemetry prometheus endpoint on http://%s:%d/metrics",
            host, bound,
        )
        return server


def exporter_address(port: int) -> Optional[Tuple[str, int]]:
    """(host, bound_port) of the exporter started for REQUESTED ``port``
    (the public way to learn which ephemeral port ``telemetry_port=0``
    actually bound), or None when none is running."""
    with _EXPORTERS_LOCK:
        server = _EXPORTERS.get(port)
    return server.server_address[:2] if server is not None else None


def shutdown_exporter(port: int) -> None:
    """Stop the exporter started for REQUESTED ``port`` (tests; production
    leaves it up). For an ephemeral exporter pass 0 — the key is the port
    you asked for, not the one the OS picked."""
    with _EXPORTERS_LOCK:
        server = _EXPORTERS.pop(port, None)
    if server is not None:
        server.shutdown()
        server.server_close()
