"""The metric/span name registry: ONE owner for every counter, stage,
gauge, and span name literal the package emits.

Six PRs of observability grew ~150 names by convention — a counter here, a
gauge there, each documented (or not) in whatever README section its PR
touched. Names that drift from their docs are worse than undocumented
ones: a dashboard keyed on ``service.shard_done`` silently reads zero
forever when the code says ``service.shards_done``. This module turns the
vocabulary into data so tools/graftlint can enforce it both ways:

- every ``METRICS.count/add/gauge/observe``/``timed``/``span``/``instant``
  /``record_span`` call site with a literal name must use a REGISTERED
  name of the right kind (rule ``vocab-unregistered``);
- every registered name must appear in the README metric docs — the
  generated vocabulary block ``vocabulary_markdown()`` emits and the
  ``vocab-docs`` rule verifies (drift in either direction fails CI).

Adding a metric is a three-line change: emit it, register it here in the
right set with a one-phrase description, and refresh the README block
(``python -m tools.graftlint --vocab-md`` prints it). The linter fails
until all three agree.

Kinds mirror tpu_tfrecord.metrics' three storage classes plus spans:

- **counters** — monotonic ``Metrics.count`` events;
- **stages** — ``Metrics.add``/``timed`` throughput totals (+ latency
  histograms), including the ``Metrics.observe``-only histogram families;
- **gauges** — ``Metrics.gauge`` instantaneous values;
- **spans** — ``telemetry.span``/``instant``/``record_span`` trace names.

Dynamically-formed names are covered by ``DYNAMIC_PREFIXES`` (e.g. the
autotuner's per-knob ``autotune.<knob>`` gauges) and ``DERIVED_SUFFIXES``
(the ``<stage>.errors`` counters ``timed`` mints, the pulse's
``<counter>.delta`` fields). Stdlib only, imports nothing from the
package — every layer (and the linter) can read it without cycles.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

__all__ = [
    "COUNTERS",
    "STAGES",
    "GAUGES",
    "SPANS",
    "DYNAMIC_PREFIXES",
    "DERIVED_SUFFIXES",
    "KINDS",
    "is_registered",
    "registered_names",
    "vocabulary_markdown",
    "VOCABULARY_BEGIN",
    "VOCABULARY_END",
]


#: Monotonic event counters (``Metrics.count``): name -> what one tick means.
COUNTERS: Dict[str, str] = {
    # -- read path robustness
    "read.corrupt_records": "corrupt frames skipped by salvage",
    "read.resyncs": "salvage re-locked onto a valid frame boundary",
    "read.retries": "transient read errors retried (incl. remote resume)",
    "read.skipped_shards": "shards dropped by on_corrupt/on_stall=skip_shard",
    "read.stalls": "reads converted to StallError by the deadline",
    "read.deadline_misses": "per-read deadlines that fired",
    "read.hedges": "straggler hedge opens issued",
    "read.hedge_wins": "hedge backup finished before the primary",
    "read.watchdog_restarts": "silent decode workers replaced",
    "read.backpressure_waits": "producer blocked on a full prefetch queue",
    # -- remote (HTTP) ingestion
    "remote.bad_range": "lying/unparseable Content-Range rejected",
    "remote.fetch_retries": "remote block fetches resumed on a fresh conn",
    # -- write path
    "write.commit_retries": "shard commit rename retried",
    "write.backpressure_waits": "encoder blocked on the committer",
    # -- columnar epoch cache
    "cache.hits": "shards served from a validated cache entry",
    "cache.misses": "shards decoded from ground truth",
    "cache.bytes_written": "bytes committed into cache entries",
    "cache.evictions": "entries removed by the LRU sweep",
    "cache.corrupt_fallbacks": "corrupt/stale entries fallen back to decode",
    "cache.populate_errors": "cache populate jobs aborted (epoch unaffected)",
    # -- autotune / telemetry plumbing
    "autotune.adjustments": "controller knob moves",
    "pulse.observer_errors": "pulse observers that raised (swallowed)",
    "pulse.tick_errors": "pulse ticks that raised (swallowed)",
    # -- fleet spool
    "fleet.spool_writes": "telemetry snapshots landed in the spool",
    "fleet.spool_errors": "snapshot attempts that failed (never raise)",
    # -- data service
    "service.registrations": "workers registered with the dispatcher",
    "service.fetches": "shard streams served by workers",
    "service.bytes_sent": "chunk bytes sent by workers",
    "service.chunks_sent": "chunks sent by workers",
    "service.chunks_recv": "chunks received by consumers",
    "service.shards_served": "shard streams completed by workers",
    "service.shards_done": "shard completions recorded by the dispatcher",
    "service.reconnects": "consumer stream reconnects",
    "service.redelivered_dropped": "duplicate chunks deduped by consumers",
    "service.lease_reassignments": "expired leases re-routed",
    "service.fallbacks": "consumers degraded to local reads",
    "service.journal_errors": "dispatcher journal writes that failed",
    "service.worker_drained": "workers that completed a graceful drain",
    "service.cache_served": "worker shard streams served from warm cache",
    "service.tenants": "distinct dataset fingerprints served",
    "service.shared_cache_hits": "shard completions that rode another job's cache",
    # -- HA: partitioned dispatchers + warm-standby failover
    "service.failovers": "standby promotions to acting primary",
    "service.fenced_writes": "journal appends rejected by the inode fence (zombie primary)",
    "service.demotions": "primaries that stopped granting leases (journal failures / fenced)",
    "service.not_primary_rejects": "lease-path ops refused by a standby or demoted primary",
    # -- elastic fleet scaler
    "elastic.scale_ups": "decode workers spawned by the scaler",
    "elastic.scale_downs": "drains initiated by the scaler",
    "elastic.drains": "workers that said goodbye after draining",
    "elastic.drained_leases": "unstarted leases handed back by drain victims",
    "elastic.spawn_errors": "worker spawns that failed",
    "elastic.step_errors": "scaler control-loop ticks that raised",
    "elastic.verdict_errors": "fleet verdict reads that failed (not idle)",
    "elastic.census_errors": "scaler ticks skipped on an unreadable partition status",
    # -- training flight recorder
    "train.steps": "completed harness train steps",
    # -- async checkpointing (snapshot/commit split)
    "ckpt.bytes_written": "checkpoint bytes committed to disk",
    "ckpt.generations_swept": "retired/dead checkpoint generations removed",
    # -- streamed serving (pipeline inference mode + the serving tier)
    "serve.requests": "generation requests completed by the serving tier",
    "serve.rejected": "requests shed at admission (queue full / draining)",
    "serve.deadline_expired": "requests dropped by their deadline (admission or in flight)",
    "serve.disconnects": "client connections lost mid-request (slots freed)",
    "serve.ticks": "continuous-batching scheduler ticks (microbatches packed)",
    "serve.errors": "serving engine ticks / completion callbacks that raised",
    "elastic.replicas_lost": "serving replicas that died undrained (SIGKILL/crash)",
}

#: Throughput stages (``Metrics.add``/``timed``) and observe-only histogram
#: families. Every entry grows records/bytes/seconds totals and (when
#: timed/observed) a latency histogram.
STAGES: Dict[str, str] = {
    "read": "raw shard bytes into the decoder",
    "read.open": "shard open (every open seam)",
    "read.io": "slab reads off the store",
    "decode": "TFRecord frame -> columnar batch",
    "h2d": "host batch -> device transfer",
    "batch.wait": "consumer blocked waiting for a batch",
    "batch": "consumer-side batch assembly",
    "write": "rows -> TFRecord shards (whole pipeline)",
    "write.encode": "example encode (native/python)",
    "write.compress": "per-slab codec compression",
    "write.io": "shard appends",
    "write.commit": "shard finalize + rename",
    "cache.open": "cache entry open + first-pass verification",
    "cache.serve": "mmap-served cached chunks",
    "cache.commit": "cache entry footer + rename",
    "train.step": "whole train step (latency histogram + spans)",
    "train.data_wait": "train step blocked in next(it)",
    "train.h2d": "train step host->device transfer",
    "train.compute": "train step device compute",
    "train.ckpt": "train step checkpoint writes",
    "ckpt.snapshot": "checkpoint snapshot (caller-thread device_get + copy)",
    "ckpt.commit": "checkpoint commit (background stage+fsync+rename)",
    "ckpt.commit_wait": "save() blocked on the previous in-flight commit",
    # dimensionless in-jit model diagnostics (histograms of fractions —
    # telemetry.DIMENSIONLESS_HIST_PREFIXES keeps them out of ms renderers)
    "moe.dropped_fraction": "tokens dropped at expert capacity (fraction)",
    "moe.gate_entropy": "router gate entropy per step",
    "moe.expert_imbalance": "max/mean routed tokens across experts",
    "pipeline.bubble_fraction": "pipeline schedule idle-tick fraction",
    "pipeline.bubble_fraction_v": "interleaved (V>1) schedule bubble fraction",
    # streamed serving: real latency histograms (not dimensionless)
    "serve.latency": "one serving request, admission -> last token",
    "serve.queue_wait": "one request's admission queue wait, admission -> first pack",
    "serve.service": "one request's service time, first pack -> last token",
}

#: Instantaneous gauges (``Metrics.gauge``): last write wins.
GAUGES: Dict[str, str] = {
    "prefetch.queue_depth": "prefetch queue fill (items)",
    "prefetch.occupancy": "EMA of prefetch queue fill fraction (verdict input)",
    "read.inflight_workers": "decode workers currently busy",
    "write.occupancy": "EMA of writer slab-queue fill (write verdict input)",
    "write.inflight_slabs": "slabs in flight in the write pipeline",
    "elastic.workers": "decode worker processes the scaler believes live",
    "elastic.replicas": "serving replicas the serving scaler believes active",
    "serve.queue_depth": "serving admission queue fill (requests waiting to start)",
    "serve.in_flight": "requests riding the serving pipeline right now",
    "service.partition": "partition index this process serves (or routes to)",
    "train.share.data_wait": "windowed share of step wall in data wait",
    "train.share.h2d": "windowed share of step wall in h2d",
    "train.share.compute": "windowed share of step wall in compute",
    "train.share.ckpt": "windowed share of step wall in checkpointing",
    "ckpt.inflight": "background checkpoint commits in flight (0 or 1)",
    "pack.density": "fraction of emitted packed tokens that are real (bin modes)",
    "lm.fsdp_param_bytes": "per-device at-rest param bytes under the fsdp layout",
    "moe.dropped_fraction": "latest per-step dropped-token fraction",
    "moe.gate_entropy": "latest per-step router gate entropy",
    "moe.expert_imbalance": "latest per-step expert imbalance",
    "pipeline.bubble_fraction": "latest per-step pipeline bubble fraction",
    "pipeline.bubble_fraction_v": "latest interleaved (V>1) bubble fraction",
}

#: Trace span / instant names (``telemetry.span``/``instant``/
#: ``record_span``; the flight-recorder and Perfetto vocabulary).
SPANS: Dict[str, str] = {
    "open": "one shard open",
    "read": "one guarded read region",
    "decode": "one chunk decode (shard-attributed)",
    "batch": "one consumer batch get",
    "write.encode": "one slab encode",
    "write.compress": "one slab compression",
    "write.io": "one slab append",
    "write.commit": "one shard commit",
    "cache.open": "one cache entry open",
    "cache.serve": "one cached chunk serve",
    "cache.commit": "one cache entry commit",
    "service.serve": "one worker shard stream",
    "train.step": "one train step (phase-decomposed)",
    "train.verdict": "windowed training verdict instant",
    "read.stall": "a read deadline fired",
    "read.retry": "a read retry was granted",
    "read.hedge": "a straggler hedge was issued",
    "read.hedge_win": "a hedge backup won",
    "watchdog_restart": "a silent worker was replaced",
    "autotune.adjust": "an autotune knob move",
    "elastic.decision": "a fleet scaler decision",
    "elastic.drain": "a drain was initiated",
    "elastic.drain_complete": "a worker finished draining",
    "service.fallback": "a consumer degraded to local reads",
    "service.lease_reassigned": "an expired lease was re-routed",
    "service.failover": "a standby took over a partition (or adopted its address)",
    "service.demoted": "a primary stopped granting leases",
    # request-scoped tracing (client-minted TraceContext over the wire)
    "serve.request": "one serving request, admission -> completion (root span)",
    "serve.queue_wait": "one request waiting for its first pack (child of serve.request)",
    "serve.tick": "one scheduler tick's slice of one request (child of serve.request)",
    "serve.shed": "a request was shed at admission (instant)",
    "serve.deadline_expired": "a request's deadline fired (instant)",
    "service.lease": "one consumer shard lease, route -> eof (root span)",
    "service.route": "dispatcher routed a shard to a worker (instant, lease-linked)",
}

#: Prefixes under which names are formed at runtime and cannot be
#: enumerated statically: kind -> (prefix, what varies).
DYNAMIC_PREFIXES: Dict[str, Dict[str, str]] = {
    "gauge": {
        "autotune.": "one gauge per tuned knob (workers, prefetch, ...)",
        "train.share.": "one gauge per train phase",
        "train.mesh.": "one gauge per mesh axis (extent)",
        "slo.": "SLO engine state per objective kind (budget remaining, window burns)",
    },
    "stage": {
        "train.": "one stage per train phase",
    },
}

#: Suffixes derived mechanically from any registered name: ``timed`` mints
#: ``<stage>.errors`` counters, the pulse emits ``<counter>.delta`` fields.
DERIVED_SUFFIXES = (".errors", ".delta")

KINDS: Dict[str, Dict[str, str]] = {
    "counter": COUNTERS,
    "stage": STAGES,
    "gauge": GAUGES,
    "span": SPANS,
}


def is_registered(name: str, kind: Optional[str] = None) -> bool:
    """Is ``name`` a registered vocabulary entry of ``kind`` (any kind when
    None)? Derived ``.errors``/``.delta`` spellings of a registered name
    and names under a registered dynamic prefix count as registered."""
    kinds = [kind] if kind is not None else list(KINDS)
    for k in kinds:
        if name in KINDS[k]:
            return True
        for prefix in DYNAMIC_PREFIXES.get(k, ()):
            if name.startswith(prefix):
                return True
    for suffix in DERIVED_SUFFIXES:
        if name.endswith(suffix) and is_registered(name[: -len(suffix)], None):
            return True
    return False


def registered_names(kind: Optional[str] = None) -> Iterable[str]:
    """Every explicitly registered name (dynamic prefixes excluded), for
    the docs-drift check."""
    if kind is not None:
        return sorted(KINDS[kind])
    out = set()
    for table in KINDS.values():
        out.update(table)
    return sorted(out)


# -- README generation -------------------------------------------------------

VOCABULARY_BEGIN = "<!-- graftlint:vocabulary:begin (generated; run python -m tools.graftlint --vocab-md) -->"
VOCABULARY_END = "<!-- graftlint:vocabulary:end -->"

_KIND_TITLES = (
    ("counter", "Counters (`Metrics.count`)"),
    ("stage", "Stages & histograms (`Metrics.add`/`timed`/`observe`)"),
    ("gauge", "Gauges (`Metrics.gauge`)"),
    ("span", "Spans & instants (`telemetry.span`/`instant`)"),
)


def vocabulary_markdown() -> str:
    """The generated README vocabulary block (between the BEGIN/END
    markers). tools/graftlint's ``vocab-docs`` rule fails when the README
    block differs from this output — regenerating is
    ``python -m tools.graftlint --vocab-md``."""
    lines = [VOCABULARY_BEGIN, ""]
    for kind, title in _KIND_TITLES:
        lines.append(f"**{title}**")
        lines.append("")
        lines.append("| name | meaning |")
        lines.append("| --- | --- |")
        for name in sorted(KINDS[kind]):
            lines.append(f"| `{name}` | {KINDS[kind][name]} |")
        dyn = DYNAMIC_PREFIXES.get(kind, {})
        for prefix in sorted(dyn):
            lines.append(f"| `{prefix}*` | {dyn[prefix]} |")
        lines.append("")
    lines.append(
        "Derived spellings: any registered name + `.errors` (counter "
        "`timed` mints on a failed block) or `.delta` (per-interval pulse "
        "field) is also registered."
    )
    lines.append("")
    lines.append(VOCABULARY_END)
    return "\n".join(lines)
