"""Profiling hooks: jax.profiler integration + device duty-cycle estimation.

The reference has no observability of its own (SURVEY.md §5: tracing ABSENT
— it rides on Spark's UI). Here the input pipeline is the product, so it can
explain itself:

- ``trace(name)``: annotates a host-side region so it shows up on the xprof
  timeline next to device ops (no-op when jax/profiler is unavailable).
  The flight recorder (tpu_tfrecord.telemetry) rides next to these: every
  span-instrumented pipeline site also holds a ``trace`` annotation, so an
  xprof capture shows the same regions the Chrome-trace export does.
- ``start_trace/stop_trace``: wrap jax.profiler for a whole capture.
- ``DutyCycle``: estimates the BASELINE.md north-star secondary metric — the
  fraction of wall time the device spends computing vs waiting on input —
  from step/wait timestamps recorded in the training loop.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional


_PROF = None
_PROF_CHECKED = False


def _profiler():
    global _PROF, _PROF_CHECKED
    if not _PROF_CHECKED:
        _PROF_CHECKED = True
        try:
            import jax.profiler as prof

            _PROF = prof
        except Exception:  # pragma: no cover - jax always present in this repo  # graftlint: swallow(no jax profiler available: tracing disabled)
            _PROF = None
    return _PROF


class _NullTrace:
    """Shared no-op context manager for the profiler-less path."""

    __slots__ = ()

    def __enter__(self) -> "_NullTrace":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NULL_TRACE = _NullTrace()


def trace(name: str):
    """Annotate a host-side region on the profiler timeline.

    Returns the profiler's TraceAnnotation directly (it IS a context
    manager) instead of wrapping it in a generator — ``trace`` sits on
    per-chunk hot paths (decode, cache serve, write stages), where the old
    ``@contextlib.contextmanager`` layer allocated a generator per call
    even with no profiler present. With jax unavailable a shared no-op is
    returned: zero allocation per call."""
    prof = _profiler()
    if prof is None:
        return _NULL_TRACE
    return prof.TraceAnnotation(name)


def start_trace(logdir: str) -> None:
    prof = _profiler()
    if prof is not None:
        prof.start_trace(logdir)


def stop_trace() -> None:
    prof = _profiler()
    if prof is not None:
        prof.stop_trace()


class DutyCycle:
    """Track device busy vs input-wait time in a training loop.

    Usage::

        duty = DutyCycle()
        for batch in it:
            with duty.wait():     # host blocked on input pipeline
                gb = make_global_batch(...)
            with duty.step():     # device computing (block_until_ready inside)
                loss = step(gb)
        print(duty.value())       # busy / (busy + wait)
    """

    def __init__(self):
        self.busy_seconds = 0.0
        self.wait_seconds = 0.0

    @contextlib.contextmanager
    def step(self):
        t0 = time.perf_counter()
        yield
        self.busy_seconds += time.perf_counter() - t0

    @contextlib.contextmanager
    def wait(self):
        t0 = time.perf_counter()
        yield
        self.wait_seconds += time.perf_counter() - t0

    def value(self) -> Optional[float]:
        total = self.busy_seconds + self.wait_seconds
        return self.busy_seconds / total if total > 0 else None
