"""DLRM dot-interaction: pairwise feature dots, as a Pallas TPU kernel.

The signature compute op of the DLRM family this framework feeds: given
per-feature embeddings E [B, F, D], emit every pairwise dot <E_i, E_j> for
i > j as a packed [B, F*(F-1)/2] tensor that is concatenated into the top
MLP input.

TPU shaping:
- the Gram matrix G = E @ E^T per sample is a batched matmul -> MXU;
- the kernel fuses the triangle extraction with the matmul while G is still
  in VMEM, so the [B, F, F] intermediate never round-trips through HBM
  (XLA materializes it between the batched-dot and the gather);
- the batch dim is tiled by the grid; F and D are small (tens), so a
  [TB, F, D] block sits comfortably in VMEM.

Gradients flow via a custom VJP whose backward is plain XLA (dE = (dG +
dG^T) @ E with dG scattered from the packed pairs) — simple, and backward is
not the hot path for inference-heavy recommenders.

`dot_interaction` picks the Pallas kernel on TPU backends and the XLA
reference elsewhere (or under `interpret=True` for CPU tests).

RETIRED from auto-dispatch (round 4): dispatch-free DEVICE-TIME
measurement on a real v5e chip (``tools/pallas_device_time.py``, fori_loop
with a data-dependency carry, two-length delta, completion forced by a
scalar fetch; full table in PARITY.md "Pallas kernel") shows XLA's
einsum+gather is faster at EVERY F — Pallas/XLA device-time ratios at
B=8192, D=32, bf16: F=8 0.27x, F=16 0.98x, F=27 0.89x, F=32 0.70x,
F=64 0.46x. The selection-matmul formulation's ~F/2 x FLOP overhead (two
[F,P] one-hot contractions vs one [F,F] Gram) costs more than the
avoided [B,F,F] HBM round-trip saves at these sizes. ``dot_interaction``
therefore defaults to the XLA path EVERYWHERE; the kernel remains as the
in-repo TEMPLATE for fusion kernels (P-tiled grid, matmul-instead-of-
gather, custom VJP) and is reachable only via ``use_pallas=True``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _tril_indices(f: int):
    rows, cols = np.tril_indices(f, k=-1)
    return rows.astype(np.int32), cols.astype(np.int32)


def dot_interaction_reference(emb: jax.Array) -> jax.Array:
    """XLA reference: [B, F, D] -> [B, F*(F-1)/2] packed lower triangle."""
    gram = jnp.einsum("bfd,bgd->bfg", emb, emb)
    rows, cols = _tril_indices(emb.shape[1])
    return gram[:, rows, cols]


def _interaction_kernel(sel_rows_ref, sel_cols_ref, emb_ref, out_ref):
    emb = emb_ref[:].astype(jnp.float32)          # [TB, F, D]
    # Gathers and unaligned reshapes don't lower to the MXU/VPU; one-hot
    # selection MATMULS do. R[tb,d,p] = E[tb, rows[p], d], same for C, then
    # the packed pairwise dots are an elementwise product reduced over D.
    contract = (((1,), (0,)), ((), ()))            # contract the F dim
    r = jax.lax.dot_general(
        emb, sel_rows_ref[:], dimension_numbers=contract,
        preferred_element_type=jnp.float32,
    )                                              # [TB, D, P]
    c = jax.lax.dot_general(
        emb, sel_cols_ref[:], dimension_numbers=contract,
        preferred_element_type=jnp.float32,
    )
    out_ref[:] = jnp.sum(r * c, axis=1).astype(out_ref.dtype)


def dot_interaction_pallas(
    emb: jax.Array,
    block_b: int = 128,
    block_p: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Pallas kernel: [B, F, D] -> [B, P] with P = F*(F-1)/2.

    B must be divisible by ``block_b`` (pad the batch otherwise — the ingest
    layer produces fixed batch sizes, so callers control this statically).
    The pair dimension P is tiled too (``block_p``, auto-sized to a VMEM
    budget): the dominant allocations are the two [TB, D, TP] f32 selection
    products, so large F (P grows as F^2) scales by shrinking TP/TB instead
    of spilling — the [B, F, F] Gram tensor still never exists in HBM.
    """
    import math

    b, f, d = emb.shape
    block_b = min(block_b, b)
    if b % block_b:
        block_b = math.gcd(b, block_b)  # largest compatible tile
    if block_b < 8 and b >= 8:
        # refuse to degrade to sub-sublane tiles silently (e.g. a prime
        # batch would run b grid steps of [1, F, D]) — pad the batch instead
        raise ValueError(
            f"batch {b} only tiles at block_b={block_b} (<8); pad the batch "
            "to a multiple of 8 or pass a compatible block_b"
        )
    rows, cols = _tril_indices(f)
    p = len(rows)
    if block_p is None:
        # budget for the two [TB, D, TP] f32 intermediates; shrink TB first
        # so TP stays a full lane multiple
        budget = 6 << 20

        def tp_for(tb: int) -> int:
            return (budget // (2 * tb * d * 4) // 128) * 128

        while block_b > 8 and tp_for(block_b) < 128:
            # shrink along DIVISORS of b only — a non-divisor tile would
            # floor-drop trailing batch rows from the grid (silent garbage)
            cands = [k for k in range(8, block_b) if b % k == 0]
            if not cands:
                break
            block_b = max(cands)
        # the 128 floor may exceed the budget for extreme D*P at this
        # block_b; results stay correct and real hardware fails loudly at
        # compile rather than silently
        block_p = max(128, tp_for(block_b))
    p_pad = -(-p // block_p) * block_p
    # one-hot selection matrices [F, P_pad]: column k picks feature rows[k]
    # (resp. cols[k]); padded columns are all-zero -> zero dots, sliced off
    sel_rows = np.zeros((f, p_pad), dtype=np.float32)
    sel_rows[rows, np.arange(p)] = 1.0
    sel_cols = np.zeros((f, p_pad), dtype=np.float32)
    sel_cols[cols, np.arange(p)] = 1.0
    out = pl.pallas_call(
        _interaction_kernel,
        out_shape=jax.ShapeDtypeStruct((b, p_pad), emb.dtype),
        grid=(b // block_b, p_pad // block_p),
        in_specs=[
            pl.BlockSpec((f, block_p), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((f, block_p), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (block_b, f, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (block_b, block_p), lambda i, j: (i, j), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(jnp.asarray(sel_rows), jnp.asarray(sel_cols), emb)
    return out[:, :p] if p_pad != p else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def dot_interaction(emb: jax.Array, use_pallas: Optional[bool] = None,
                    block_b: int = 128, interpret: bool = False) -> jax.Array:
    """Packed pairwise dots with autodiff.

    Auto-dispatch (use_pallas=None) resolves to the XLA path everywhere:
    measured device time on a real v5e shows XLA faster at every F (module
    docstring / PARITY.md). The Pallas kernel is opt-in (use_pallas=True)
    as a template; callers inside a shard_map pass it per-device shapes.
    """
    return _forward(emb, use_pallas, block_b, interpret)


def _forward(emb, use_pallas, block_b, interpret):
    if use_pallas is None:
        # Retired from auto-dispatch: v5e device-time table (PARITY.md)
        # shows XLA's einsum+gather faster at every F measured.
        use_pallas = False
    if use_pallas:
        return dot_interaction_pallas(emb, block_b=block_b, interpret=interpret)
    return dot_interaction_reference(emb)


def _fwd(emb, use_pallas, block_b, interpret):
    return _forward(emb, use_pallas, block_b, interpret), emb


def _bwd(use_pallas, block_b, interpret, emb, g):
    # out[b, p] = sum_d E[b, rows[p], d] * E[b, cols[p], d]
    # dE = (dG + dG^T) @ E with dG scattered from the packed pairs.
    b, f, d = emb.shape
    rows, cols = _tril_indices(f)
    dgram = jnp.zeros((b, f, f), dtype=jnp.float32)
    dgram = dgram.at[:, rows, cols].set(g.astype(jnp.float32))
    sym = dgram + jnp.swapaxes(dgram, 1, 2)
    demb = jnp.einsum("bfg,bgd->bfd", sym, emb.astype(jnp.float32))
    return (demb.astype(emb.dtype),)


dot_interaction.defvjp(_fwd, _bwd)
