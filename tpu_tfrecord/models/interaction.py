"""DLRM dot-interaction: pairwise feature dots, as a Pallas TPU kernel.

The signature compute op of the DLRM family this framework feeds: given
per-feature embeddings E [B, F, D], emit every pairwise dot <E_i, E_j> for
i > j as a packed [B, F*(F-1)/2] tensor that is concatenated into the top
MLP input.

TPU shaping:
- the Gram matrix G = E @ E^T per sample is a batched matmul -> MXU;
- the kernel fuses the triangle extraction with the matmul while G is still
  in VMEM, so the [B, F, F] intermediate never round-trips through HBM
  (XLA materializes it between the batched-dot and the gather);
- the batch dim is tiled by the grid; F and D are small (tens), so a
  [TB, F, D] block sits comfortably in VMEM.

Gradients flow via a custom VJP whose backward is plain XLA (dE = (dG +
dG^T) @ E with dG scattered from the packed pairs) — simple, and backward is
not the hot path for inference-heavy recommenders.

`dot_interaction` picks the Pallas kernel on TPU backends and the XLA
reference elsewhere (or under `interpret=True` for CPU tests).

Measured on one v5e chip (B=1024, F=27, D=32, bf16): parity with XLA's
fused path (~1.5ms/call both) — at this F the XLA gather fusion is already
good; the kernel's win is keeping the Gram block VMEM-resident (no [B,F,F]
HBM round-trip), which grows with F, plus serving as the template for
fusing more of the interaction stack.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _tril_indices(f: int):
    rows, cols = np.tril_indices(f, k=-1)
    return rows.astype(np.int32), cols.astype(np.int32)


def dot_interaction_reference(emb: jax.Array) -> jax.Array:
    """XLA reference: [B, F, D] -> [B, F*(F-1)/2] packed lower triangle."""
    gram = jnp.einsum("bfd,bgd->bfg", emb, emb)
    rows, cols = _tril_indices(emb.shape[1])
    return gram[:, rows, cols]


def _interaction_kernel(sel_rows_ref, sel_cols_ref, emb_ref, out_ref):
    emb = emb_ref[:].astype(jnp.float32)          # [TB, F, D]
    # Gathers and unaligned reshapes don't lower to the MXU/VPU; one-hot
    # selection MATMULS do. R[tb,d,p] = E[tb, rows[p], d], same for C, then
    # the packed pairwise dots are an elementwise product reduced over D.
    contract = (((1,), (0,)), ((), ()))            # contract the F dim
    r = jax.lax.dot_general(
        emb, sel_rows_ref[:], dimension_numbers=contract,
        preferred_element_type=jnp.float32,
    )                                              # [TB, D, P]
    c = jax.lax.dot_general(
        emb, sel_cols_ref[:], dimension_numbers=contract,
        preferred_element_type=jnp.float32,
    )
    out_ref[:] = jnp.sum(r * c, axis=1).astype(out_ref.dtype)


def dot_interaction_pallas(
    emb: jax.Array, block_b: int = 128, interpret: bool = False
) -> jax.Array:
    """Pallas kernel: [B, F, D] -> [B, P] with P = F*(F-1)/2.

    B must be divisible by ``block_b`` (pad the batch otherwise — the ingest
    layer produces fixed batch sizes, so callers control this statically).
    """
    import math

    b, f, d = emb.shape
    block_b = min(block_b, b)
    if b % block_b:
        block_b = math.gcd(b, block_b)  # largest compatible tile
    if block_b < 8 and b >= 8:
        # refuse to degrade to sub-sublane tiles silently (e.g. a prime
        # batch would run b grid steps of [1, F, D]) — pad the batch instead
        raise ValueError(
            f"batch {b} only tiles at block_b={block_b} (<8); pad the batch "
            "to a multiple of 8 or pass a compatible block_b"
        )
    rows, cols = _tril_indices(f)
    p = len(rows)
    # one-hot selection matrices [F, P]: column k picks feature rows[k]
    # (resp. cols[k])
    sel_rows = np.zeros((f, p), dtype=np.float32)
    sel_rows[rows, np.arange(p)] = 1.0
    sel_cols = np.zeros((f, p), dtype=np.float32)
    sel_cols[cols, np.arange(p)] = 1.0
    return pl.pallas_call(
        _interaction_kernel,
        out_shape=jax.ShapeDtypeStruct((b, p), emb.dtype),
        grid=(b // block_b,),
        in_specs=[
            pl.BlockSpec((f, p), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((f, p), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, f, d), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_b, p), lambda i: (i, 0), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(jnp.asarray(sel_rows), jnp.asarray(sel_cols), emb)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def dot_interaction(emb: jax.Array, use_pallas: Optional[bool] = None,
                    block_b: int = 128, interpret: bool = False) -> jax.Array:
    """Packed pairwise dots with autodiff; Pallas forward on TPU.

    Auto-dispatch (use_pallas=None) picks the kernel only on SINGLE-device
    TPU backends: an un-annotated pallas_call inside a jit over a sharded
    mesh would defeat GSPMD partitioning. Multi-chip users call it with
    use_pallas=True from inside their own shard_map (per-device shapes).
    """
    return _forward(emb, use_pallas, block_b, interpret)


def _forward(emb, use_pallas, block_b, interpret):
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu" and jax.device_count() == 1
    if use_pallas:
        return dot_interaction_pallas(emb, block_b=block_b, interpret=interpret)
    return dot_interaction_reference(emb)


def _fwd(emb, use_pallas, block_b, interpret):
    return _forward(emb, use_pallas, block_b, interpret), emb


def _bwd(use_pallas, block_b, interpret, emb, g):
    # out[b, p] = sum_d E[b, rows[p], d] * E[b, cols[p], d]
    # dE = (dG + dG^T) @ E with dG scattered from the packed pairs.
    b, f, d = emb.shape
    rows, cols = _tril_indices(f)
    dgram = jnp.zeros((b, f, f), dtype=jnp.float32)
    dgram = dgram.at[:, rows, cols].set(g.astype(jnp.float32))
    sym = dgram + jnp.swapaxes(dgram, 1, 2)
    demb = jnp.einsum("bfg,bgd->bfd", sym, emb.astype(jnp.float32))
    return (demb.astype(emb.dtype),)


dot_interaction.defvjp(_fwd, _bwd)
