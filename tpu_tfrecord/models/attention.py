"""Ring attention: sequence-parallel attention for long contexts.

The long-context compute primitive this framework's ingestion feeds: a
sequence sharded over a mesh axis (the padded [B, L, ...] arrays produced by
tpu_tfrecord.tpu.ingest with L on a 'seq' axis) attends over its FULL length
while no device ever holds more than its L/P chunk of K/V.

TPU-idiomatic construction:
- `shard_map` over the sequence axis; K/V blocks rotate around the ring with
  `lax.ppermute` (neighbor hops ride the ICI torus; nothing goes through
  host or DCN). The batch dim can stay sharded on a 'data' axis.
- flash-style online softmax: running max / denominator / output accumulate
  per step, so memory is O(L_chunk^2) per device instead of O(L^2), and the
  result is EXACT (not an approximation).
- the rotation runs p-1 times inside one `lax.fori_loop` (the final block
  needs no outgoing hop), one compiled program, no data-dependent Python
  control flow.
- `lengths` masks padded key positions — the `<name>_len` arrays the ingest
  layer emits plug in directly, so pad tokens never receive softmax mass.

`ring_attention` is the sharded entry point; `attention_reference` is the
plain dense oracle used by the tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG = jnp.float32(-1e30)  # mask value; avoids inf-inf NaNs for empty rows


def attention_reference(q, k, v, lengths=None, scale: Optional[float] = None):
    """Dense softmax attention oracle. q,k,v: [B, L, H, D] -> [B, L, H, D]."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) * scale
    if lengths is not None:
        valid = jnp.arange(k.shape[1])[None, :] < lengths[:, None]  # [B, M]
        scores = jnp.where(valid[:, None, None, :], scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v.astype(jnp.float32)).astype(q.dtype)


def _ring_attention_local(q, k, v, lengths, scale: float, axis_name: str):
    """Per-device body (inside shard_map): q,k,v are the local sequence
    chunks [B, Lc, H, D]; K/V rotate one neighbor per step."""
    p = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, lc, h, d = q.shape
    positions = jnp.arange(lc)

    def accumulate(step_i, k_blk, v_blk, m, l, o):
        scores = (
            jnp.einsum("blhd,bmhd->bhlm", q, k_blk).astype(jnp.float32) * scale
        )  # [B, H, Lc, Lk]
        if lengths is not None:
            # the block arriving at ring step s originated on device
            # (idx - s) mod p: its keys cover global positions src*Lc + j
            src = jax.lax.rem(idx - step_i + p, p)
            key_pos = src * lc + positions                    # [Lk]
            valid = key_pos[None, :] < lengths[:, None]       # [B, Lk]
            scores = jnp.where(valid[:, None, None, :], scores, _NEG)
        blk_max = scores.max(axis=-1)                         # [B, H, Lc]
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)                             # rescale old sums
        probs = jnp.exp(scores - new_m[..., None])            # [B, H, Lc, Lk]
        l = l * corr + probs.sum(axis=-1)
        upd = jnp.einsum("bhlm,bmhd->blhd", probs, v_blk.astype(jnp.float32))
        o = o * corr.transpose(0, 2, 1)[..., None] + upd
        return new_m, l, o

    # Accumulators are per-device state: derive them from q so they carry
    # exactly q's varying axes (seq, and data when the batch is sharded) —
    # a fresh constant would mismatch the fori_loop carry type.
    zero_bhl = jnp.moveaxis(q[..., 0], 1, 2).astype(jnp.float32) * 0.0  # [B,H,Lc]
    m0 = zero_bhl + _NEG
    l0 = zero_bhl
    o0 = q.astype(jnp.float32) * 0.0
    perm = [(j, (j + 1) % p) for j in range(p)]

    def step(i, carry):
        k_blk, v_blk, m, l, o = carry
        m, l, o = accumulate(i, k_blk, v_blk, m, l, o)
        # rotate K/V one neighbor around the ring (ICI hop); runs only for
        # the first p-1 blocks — the last block needs no outgoing hop
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    k_blk, v_blk, m, l, o = jax.lax.fori_loop(0, p - 1, step, (k, v, m0, l0, o0))
    _, l, o = accumulate(p - 1, k_blk, v_blk, m, l, o)
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    data_axis: Optional[str] = None,
    lengths: Optional[jax.Array] = None,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention over a sequence sharded on ``mesh[seq_axis]``.

    q,k,v: [B, L, H, D] with L divisible by the axis size. Pass
    ``data_axis`` to keep the batch dim sharded (otherwise it is treated as
    replicated — an unsharded spec on a sharded batch would silently gather
    it to every device). ``lengths`` [B] masks padded key positions (the
    ingest layer's ``<name>_len`` output).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    spec = P(data_axis, seq_axis, None, None)
    len_spec = P(data_axis)
    if lengths is None:
        fn = jax.shard_map(
            functools.partial(
                _ring_attention_local, lengths=None, scale=scale, axis_name=seq_axis
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
        return fn(q, k, v)
    fn = jax.shard_map(
        functools.partial(_ring_attention_local, scale=scale, axis_name=seq_axis),
        mesh=mesh,
        in_specs=(spec, spec, spec, len_spec),
        out_specs=spec,
    )
    return fn(q, k, v, lengths)
