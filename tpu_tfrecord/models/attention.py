"""Sequence-parallel attention for long contexts: ring and all-to-all.

The long-context compute primitives this framework's ingestion feeds: a
sequence sharded over a mesh axis (the padded [B, L, ...] arrays produced by
tpu_tfrecord.tpu.ingest with L on a 'seq' axis) attends over its FULL length
while no device ever holds more than its L/P chunk of the INPUT.

Two TPU-idiomatic constructions (SURVEY.md: "ring attention or all-to-all
sequence/context parallelism"), same exact math, different collective
pattern — pick by sequence length and head count:

- `ring_attention`: `shard_map` over the sequence axis; K/V blocks rotate
  around the ring with `lax.ppermute` (neighbor hops ride the ICI torus;
  nothing goes through host or DCN). Flash-style online softmax keeps
  per-device memory O(L_chunk^2), so it scales to sequences that do not
  fit any single device. p-1 rotation steps inside one `lax.fori_loop`.
- `ulysses_attention` (DeepSpeed-Ulysses pattern, arXiv:2309.14509):
  two `lax.all_to_all` exchanges re-shard [B, L/p, H, D] -> [B, L, H/p, D],
  each device runs DENSE attention over the full sequence for its H/p head
  group, then the inverse exchange restores sequence sharding. Communication
  is 2 all-to-alls of the activations — O(B*L*H*D/p) per device, constant in
  p hops — vs the ring's p-1 K/V rotations, so it wins at moderate L with
  enough heads; per-device scores are O(B * H/p * L^2), so VERY long
  sequences still want the ring. Requires H % p == 0.

Both accept `lengths` to mask padded key positions — the `<name>_len`
arrays the ingest layer emits plug in directly, so pad tokens never receive
softmax mass — and `causal=True` for decoder/LM masking (the ring masks by
GLOBAL key position across rotated blocks; ulysses applies the standard
triangle locally after the exchange, where each device holds the full
sequence).

The causal ring has two layouts: the default contiguous one computes-
then-masks future blocks (device 0 ends with 1 useful block, device p-1
with p — the last device sets wall-clock), while ``zigzag=True`` re-
stripes internally (device i owns strip 2i AND its mirror 2p-1-2i) so every
device holds the same number of unmasked (q, k) pairs — the standard
balanced causal ring schedule — at the cost of one O(L*H*D) permute each
way; callers keep the contiguous contract on both sides.
`attention_reference` is the plain dense oracle used by the tests.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_tfrecord.models._compat import axis_size, shard_map

_NEG = jnp.float32(-1e30)  # mask value; avoids inf-inf NaNs for empty rows


def _expand_kv(q, kv):
    """GQA: repeat K/V head groups to match q's head count (no-op for MHA).
    q [B,L,H,D], kv [B,M,Hkv,D] with H % Hkv == 0 -> [B,M,H,D]."""
    h, hkv = q.shape[2], kv.shape[2]
    if h == hkv:
        return kv
    if h % hkv:
        raise ValueError(
            f"GQA needs num_heads % num_kv_heads == 0 (got H={h}, Hkv={hkv})"
        )
    return jnp.repeat(kv, h // hkv, axis=2)


def attention_reference(
    q, k, v, lengths=None, scale: Optional[float] = None, causal: bool = False,
    segments=None,
):
    """Dense softmax attention oracle. q [B, L, H, D], k/v [B, L, Hkv, D]
    with Hkv == H (MHA) or H % Hkv == 0 (GQA/MQA: each K/V head serves
    H/Hkv query heads) -> [B, L, H, D]. ``causal`` masks keys after each
    query position (decoder/LM attention). ``segments`` [B, L] int makes
    the mask block-diagonal within the causal triangle: position i attends
    to j only when segments[b, i] == segments[b, j], so documents packed
    into one row (TokenPacker's bin modes) never leak mass across their
    boundaries."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    k, v = _expand_kv(q, k), _expand_kv(q, v)
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) * scale
    if lengths is not None:
        valid = jnp.arange(k.shape[1])[None, :] < lengths[:, None]  # [B, M]
        scores = jnp.where(valid[:, None, None, :], scores, _NEG)
    if segments is not None:
        same = segments[:, :, None] == segments[:, None, :]       # [B, L, M]
        scores = jnp.where(same[:, None, :, :], scores, _NEG)
    if causal:
        l, m = q.shape[1], k.shape[1]
        tri = jnp.arange(m)[None, :] <= jnp.arange(l)[:, None]    # [L, M]
        scores = jnp.where(tri[None, None, :, :], scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v.astype(jnp.float32)).astype(q.dtype)


def _ring_attention_local(
    q, k, v, lengths, scale: float, axis_name: str, causal: bool = False,
    zigzag: bool = False, segments=None,
):
    """Per-device body (inside shard_map): q,k,v are the local sequence
    chunks [B, Lc, H, D]; K/V rotate one neighbor per step.

    ``zigzag`` (causal only): the balanced causal-ring schedule. One
    ppermute involution swaps second chunk-halves between device j and
    p-1-j, so device j owns strip 2j AND its mirror 2p-1-2j (strip size
    Lc/2). Every (device, step) then needs exactly HALF the score matrix
    — either one k-half against all q rows or all keys against one
    q-half, both strictly unmasked by construction — computed via
    lax.cond'd half-block einsums (the diagonal step keeps the full
    masked block). Work is balanced per step AND per device, at half the
    dense FLOPs; the output swaps back before return, so callers keep the
    contiguous [B, L, ...] contract end to end.

    ``segments`` [B, Lc] (the local chunk of a [B, L] per-position segment
    id array) adds the packed-document block-diagonal mask: a segment
    block rides every K/V rotation (and the zigzag restripe), and EVERY
    fold path applies it — the zigzag half blocks are causally unmasked
    by construction but still cross document boundaries, so the segment
    mask is orthogonal to the causal one there."""
    p = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    if zigzag:
        swap = [(j, p - 1 - j) for j in range(p)]
        half = q.shape[1] // 2

        def restripe(x):
            other = jax.lax.ppermute(x[:, half:], axis_name, swap)
            return jnp.concatenate([x[:, :half], other], axis=1)

        q, k, v = restripe(q), restripe(k), restripe(v)
        if segments is not None:
            segments = restripe(segments)
    b, lc, h, d = q.shape
    positions = jnp.arange(lc)

    def dev_pos(dev):
        """Global positions of device ``dev``'s local rows."""
        if zigzag:
            s = lc // 2
            half_ar = jnp.arange(s)
            return jnp.concatenate(
                [2 * dev * s + half_ar, (2 * p - 1 - 2 * dev) * s + half_ar]
            )
        return dev * lc + positions

    def online_update(scores, v_rows, m, l, o):
        """One online-softmax fold of ``scores`` [B,H,R,K] with values
        ``v_rows`` [B,K,H,D] into accumulators covering the same R rows —
        the ONE implementation every path (full, half-k, half-q) folds
        through."""
        blk_max = scores.max(axis=-1)
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)                             # rescale old sums
        probs = jnp.exp(scores - new_m[..., None])
        l = l * corr + probs.sum(axis=-1)
        upd = jnp.einsum("bhlm,bmhd->blhd", probs, v_rows.astype(jnp.float32))
        o = o * corr.transpose(0, 2, 1)[..., None] + upd
        return new_m, l, o

    def accumulate(step_i, k_blk, v_blk, seg_blk, m, l, o):
        # GQA: the rotating blocks carry only Hkv heads (comm-optimal);
        # repeat to H locally — XLA fuses the broadcast into the einsum
        scores = (
            jnp.einsum("blhd,bmhd->bhlm", q, _expand_kv(q, k_blk)).astype(
                jnp.float32
            )
            * scale
        )  # [B, H, Lc, Lk]
        # the block arriving at ring step s originated on device
        # (idx - s) mod p: its keys cover that device's global positions
        src = jax.lax.rem(idx - step_i + p, p)
        key_pos = dev_pos(src)                                # [Lk]
        if lengths is not None:
            valid = key_pos[None, :] < lengths[:, None]       # [B, Lk]
            scores = jnp.where(valid[:, None, None, :], scores, _NEG)
        if segments is not None:
            same = segments[:, :, None] == seg_blk[:, None, :]  # [B, Lq, Lk]
            scores = jnp.where(same[:, None, :, :], scores, _NEG)
        if causal:
            # mask by GLOBAL positions; a fully-future block masks to _NEG
            # everywhere and contributes ~0 mass (the m0=-1e30 floor keeps
            # the online softmax finite)
            q_pos = dev_pos(idx)                              # [Lq]
            tri = key_pos[None, :] <= q_pos[:, None]          # [Lq, Lk]
            scores = jnp.where(tri[None, None, :, :], scores, _NEG)
        return online_update(scores, _expand_kv(q, v_blk), m, l, o)

    def accumulate_zigzag(step_i, k_blk, v_blk, seg_blk, m, l, o):
        """Balanced causal step for NON-diagonal blocks (step_i >= 1; step
        0 is the device's own block — the causal diagonal — folded once
        through ``accumulate`` before the loop): exactly HALF the score
        matrix is needed and that half is strictly unmasked (CAUSALLY) by
        strip construction, so only it is computed; the segment mask still
        applies to it — packed-document boundaries do not follow strips."""
        s = lc // 2
        src = jax.lax.rem(idx - step_i + p, p)
        key_pos = dev_pos(src)

        def len_mask(scores, kp):
            if lengths is None:
                return scores
            valid = kp[None, :] < lengths[:, None]
            return jnp.where(valid[:, None, None, :], scores, _NEG)

        def seg_mask(scores, sq, sk):
            # sq [B, R] query-side ids, sk [B, K] key-side ids for exactly
            # the rows/keys this half fold touches
            if segments is None:
                return scores
            same = sq[:, :, None] == sk[:, None, :]
            return jnp.where(same[:, None, :, :], scores, _NEG)

        # both half-starts share the same selector: the EARLY half when the
        # block comes from a lower rank, the LATE half otherwise
        start = jnp.where(src < idx, 0, s)

        def half_k(m, l, o):
            # one k-half against ALL q rows (strictly unmasked quadrants)
            kh = jax.lax.dynamic_slice_in_dim(k_blk, start, s, axis=1)
            vh = jax.lax.dynamic_slice_in_dim(v_blk, start, s, axis=1)
            kp = jax.lax.dynamic_slice_in_dim(key_pos, start, s, axis=0)
            scores = (
                jnp.einsum("blhd,bmhd->bhlm", q, _expand_kv(q, kh)).astype(
                    jnp.float32
                )
                * scale
            )
            scores = len_mask(scores, kp)
            if segments is not None:
                skh = jax.lax.dynamic_slice_in_dim(seg_blk, start, s, axis=1)
                scores = seg_mask(scores, segments, skh)
            return online_update(scores, _expand_kv(q, vh), m, l, o)

        def half_q(m, l, o):
            # all keys against ONE q-half: fold into that half's slice of
            # the accumulators only
            qh = jax.lax.dynamic_slice_in_dim(q, start, s, axis=1)
            scores = (
                jnp.einsum("blhd,bmhd->bhlm", qh, _expand_kv(q, k_blk)).astype(
                    jnp.float32
                )
                * scale
            )
            scores = len_mask(scores, key_pos)
            if segments is not None:
                sqh = jax.lax.dynamic_slice_in_dim(segments, start, s, axis=1)
                scores = seg_mask(scores, sqh, seg_blk)
            ms = jax.lax.dynamic_slice_in_dim(m, start, s, axis=2)
            ls = jax.lax.dynamic_slice_in_dim(l, start, s, axis=2)
            os_ = jax.lax.dynamic_slice_in_dim(o, start, s, axis=1)
            ms, ls, os_ = online_update(scores, _expand_kv(q, v_blk), ms, ls, os_)
            return (
                jax.lax.dynamic_update_slice_in_dim(m, ms, start, axis=2),
                jax.lax.dynamic_update_slice_in_dim(l, ls, start, axis=2),
                jax.lax.dynamic_update_slice_in_dim(o, os_, start, axis=1),
            )

        # half-k when (src < idx) agrees with (src + idx <= p - 1); the
        # complementary off-diagonal cases are half-q (derivation in the
        # PARITY zigzag note)
        pred_a = (src < idx) == (src + idx <= p - 1)
        return jax.lax.cond(
            pred_a, lambda t: half_k(*t), lambda t: half_q(*t), (m, l, o)
        )

    # Accumulators are per-device state: derive them from q so they carry
    # exactly q's varying axes (seq, and data when the batch is sharded) —
    # a fresh constant would mismatch the fori_loop carry type.
    zero_bhl = jnp.moveaxis(q[..., 0], 1, 2).astype(jnp.float32) * 0.0  # [B,H,Lc]
    m0 = zero_bhl + _NEG
    l0 = zero_bhl
    o0 = q.astype(jnp.float32) * 0.0
    perm = [(j, (j + 1) % p) for j in range(p)]
    # Step 0 is always the device's OWN block — the causal diagonal — so
    # the full masked fold happens exactly once, hoisted out of the loop;
    # the loop body then carries only the half-block program under zigzag.
    m, l, o = accumulate(0, k, v, segments, m0, l0, o0)
    if p > 1:
        rest = accumulate_zigzag if (zigzag and causal) else accumulate
        # rotate K/V one neighbor around the ring (ICI hop); p-1 hops in
        # total — the final block needs no outgoing hop. Segment ids ride
        # the same hops so every arriving block knows its document ids.
        k_blk = jax.lax.ppermute(k, axis_name, perm)
        v_blk = jax.lax.ppermute(v, axis_name, perm)
        s_blk = (
            jax.lax.ppermute(segments, axis_name, perm)
            if segments is not None else None
        )

        def step(i, carry):
            if segments is None:
                k_blk, v_blk, m, l, o = carry
                s_cur = None
            else:
                k_blk, v_blk, s_cur, m, l, o = carry
            m, l, o = rest(i, k_blk, v_blk, s_cur, m, l, o)
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            if segments is None:
                return k_blk, v_blk, m, l, o
            s_cur = jax.lax.ppermute(s_cur, axis_name, perm)
            return k_blk, v_blk, s_cur, m, l, o

        carry0 = (
            (k_blk, v_blk, m, l, o) if segments is None
            else (k_blk, v_blk, s_blk, m, l, o)
        )
        out_carry = jax.lax.fori_loop(1, p - 1, step, carry0)
        if segments is None:
            k_blk, v_blk, m, l, o = out_carry
            s_blk = None
        else:
            k_blk, v_blk, s_blk, m, l, o = out_carry
        m, l, o = rest(p - 1, k_blk, v_blk, s_blk, m, l, o)
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    if zigzag:
        out = restripe(out)  # the half-swap is an involution: swap back
    return out.astype(q.dtype)


def _shard_map_attention(
    local_fn, q, k, v, mesh, seq_axis, data_axis, lengths, scale,
    causal=False, segments=None, **local_kwargs,
):
    """Shared dispatch for both SP flavors: one shard_map over the sequence
    axis (batch optionally on ``data_axis`` — an unsharded spec on a sharded
    batch would silently gather it to every device), ``lengths`` riding
    along per-batch and ``segments`` [B, L] per-position (sharded like the
    sequence itself) when given."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    spec = P(data_axis, seq_axis, None, None)
    in_specs = [spec, spec, spec]
    args = [q, k, v]
    if lengths is not None:
        in_specs.append(P(data_axis))
        args.append(lengths)
    if segments is not None:
        in_specs.append(P(data_axis, seq_axis))
        args.append(segments)

    def body(*arrs):
        qb, kb, vb = arrs[:3]
        j = 3
        lb = sb = None
        if lengths is not None:
            lb = arrs[j]
            j += 1
        if segments is not None:
            sb = arrs[j]
        return local_fn(
            qb, kb, vb, lengths=lb, scale=scale, axis_name=seq_axis,
            causal=causal, segments=sb, **local_kwargs,
        )

    fn = shard_map(
        body, mesh=mesh, in_specs=tuple(in_specs), out_specs=spec
    )
    return fn(*args)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    data_axis: Optional[str] = None,
    lengths: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    causal: bool = False,
    zigzag: bool = False,
    segments: Optional[jax.Array] = None,
) -> jax.Array:
    """Exact attention over a sequence sharded on ``mesh[seq_axis]``.

    q: [B, L, H, D]; k,v: [B, L, Hkv, D] with Hkv == H (MHA) or any
    positive divisor of H (GQA/MQA — only the Hkv heads rotate the ring,
    the group repeat fuses locally). L divisible by the axis size. Pass
    ``data_axis`` to keep the batch dim sharded. ``lengths`` [B] masks
    padded key positions (the ingest layer's ``<name>_len`` output).
    ``segments`` [B, L] int ids make the mask block-diagonal across
    packed documents (see `attention_reference`); the ids shard on the
    sequence axis and ride the K/V ring rotations.

    ``zigzag`` (causal only): the balanced causal-ring schedule. One
    ppermute involution inside the kernel swaps second chunk-halves
    between device j and p-1-j, giving each device one early strip and
    its mirror; every non-diagonal ring step then computes only the half
    of the score matrix that is unmasked by construction (lax.cond'd
    half-block einsums) — HALF the dense causal FLOPs, balanced per step
    and per device — and the output swaps back, so callers keep the
    contiguous [B, L, ...] contract on both sides. Needs
    L % (2 * axis size) == 0. The swap moves O(L*H*D/p) bytes per device
    each way vs the O(L^2) attention it balances.
    """
    if zigzag:
        if not causal:
            raise ValueError(
                "zigzag re-striping only changes anything for causal "
                "attention; pass causal=True or drop zigzag"
            )
        if q.shape[1] % (2 * mesh.shape[seq_axis]):
            raise ValueError(
                f"zigzag needs sequence length % (2 * mesh['{seq_axis}']) "
                f"== 0 (got L={q.shape[1]}, axis size "
                f"{mesh.shape[seq_axis]})"
            )
    if segments is not None and segments.shape != q.shape[:2]:
        raise ValueError(
            f"segments shape {segments.shape} != batch/sequence dims "
            f"{q.shape[:2]} of q"
        )
    return _shard_map_attention(
        _ring_attention_local, q, k, v, mesh, seq_axis, data_axis, lengths,
        scale, causal, segments=segments, zigzag=zigzag,
    )


def _ulysses_attention_local(
    q, k, v, lengths, scale: float, axis_name: str, causal: bool = False,
    segments=None,
):
    """Per-device body (inside shard_map): q,k,v are the local sequence
    chunks [B, Lc, H, D]. Two all-to-alls re-shard sequence<->heads; the
    attention itself is plain dense math over the full sequence for this
    device's H/p head group."""
    # [B, Lc, H, D] -> [B, L, H/p, D]: every device sends each peer its
    # chunk of that peer's head group — one tiled all_to_all on the ICI
    qh, kh, vh = (
        jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)
        for x in (q, k, v)
    )
    if segments is not None:
        # post-exchange attention spans the full sequence, so every device
        # needs every segment id — an all_gather of [B, Lc] ints, trivial
        # next to the activation all-to-alls
        segments = jax.lax.all_gather(
            segments, axis_name, axis=1, tiled=True
        )
    # post-exchange each device holds the FULL sequence for its head
    # group, so the dense oracle's local causal mask IS the global one
    out = attention_reference(
        qh, kh, vh, lengths=lengths, scale=scale, causal=causal,
        segments=segments,
    )
    # inverse exchange: [B, L, H/p, D] -> [B, Lc, H, D]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    data_axis: Optional[str] = None,
    lengths: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    causal: bool = False,
    segments: Optional[jax.Array] = None,
) -> jax.Array:
    """Exact attention over a sequence sharded on ``mesh[seq_axis]`` via the
    all-to-all (DeepSpeed-Ulysses) pattern — same contract and results as
    :func:`ring_attention` (including ``segments`` packed-document
    masking), different collective/memory profile (see module docstring
    for when to pick which).

    q: [B, L, H, D]; k,v: [B, L, Hkv, D] (GQA: Hkv a positive divisor of
    H). L, H, AND Hkv must all be divisible by the axis size — each device
    owns a head group while attending over the full sequence, so MQA
    (Hkv=1) on a >1 axis is ring-only. ``lengths`` [B] masks padded key
    positions.
    """
    p = mesh.shape[seq_axis]
    h, hkv = q.shape[2], k.shape[2]
    if h % p or hkv % p:
        raise ValueError(
            f"ulysses_attention needs num_heads % mesh['{seq_axis}'] == 0 "
            f"for q AND k/v (got H={h}, Hkv={hkv}, axis size {p}); use "
            f"ring_attention when heads cannot cover the sequence axis"
        )
    # H % Hkv is guarded once, in _expand_kv (shared with the ring flavor)
    return _shard_map_attention(
        _ulysses_attention_local, q, k, v, mesh, seq_axis, data_axis, lengths,
        scale, causal, segments=segments,
    )
