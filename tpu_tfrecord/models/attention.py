"""Sequence-parallel attention for long contexts: ring and all-to-all.

The long-context compute primitives this framework's ingestion feeds: a
sequence sharded over a mesh axis (the padded [B, L, ...] arrays produced by
tpu_tfrecord.tpu.ingest with L on a 'seq' axis) attends over its FULL length
while no device ever holds more than its L/P chunk of the INPUT.

Two TPU-idiomatic constructions (SURVEY.md: "ring attention or all-to-all
sequence/context parallelism"), same exact math, different collective
pattern — pick by sequence length and head count:

- `ring_attention`: `shard_map` over the sequence axis; K/V blocks rotate
  around the ring with `lax.ppermute` (neighbor hops ride the ICI torus;
  nothing goes through host or DCN). Flash-style online softmax keeps
  per-device memory O(L_chunk^2), so it scales to sequences that do not
  fit any single device. p-1 rotation steps inside one `lax.fori_loop`.
- `ulysses_attention` (DeepSpeed-Ulysses pattern, arXiv:2309.14509):
  two `lax.all_to_all` exchanges re-shard [B, L/p, H, D] -> [B, L, H/p, D],
  each device runs DENSE attention over the full sequence for its H/p head
  group, then the inverse exchange restores sequence sharding. Communication
  is 2 all-to-alls of the activations — O(B*L*H*D/p) per device, constant in
  p hops — vs the ring's p-1 K/V rotations, so it wins at moderate L with
  enough heads; per-device scores are O(B * H/p * L^2), so VERY long
  sequences still want the ring. Requires H % p == 0.

Both accept `lengths` to mask padded key positions — the `<name>_len`
arrays the ingest layer emits plug in directly, so pad tokens never receive
softmax mass — and `causal=True` for decoder/LM masking (the ring masks by
GLOBAL key position across rotated blocks; ulysses applies the standard
triangle locally after the exchange, where each device holds the full
sequence).

Known limitation (efficiency, not correctness): the causal ring keeps the
contiguous block layout, so fully-future blocks are computed then masked —
~2x the necessary FLOPs, and the last ring device sets the wall-clock.
The standard fix is zigzag/striped block assignment (each device owns
strips i and 2p-1-i), which balances useful work but re-striped the global
sequence layout — a follow-up that changes the input contract, so it is
deliberately not bundled into this flag. `attention_reference` is the
plain dense oracle used by the tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG = jnp.float32(-1e30)  # mask value; avoids inf-inf NaNs for empty rows


def _expand_kv(q, kv):
    """GQA: repeat K/V head groups to match q's head count (no-op for MHA).
    q [B,L,H,D], kv [B,M,Hkv,D] with H % Hkv == 0 -> [B,M,H,D]."""
    h, hkv = q.shape[2], kv.shape[2]
    if h == hkv:
        return kv
    if h % hkv:
        raise ValueError(
            f"GQA needs num_heads % num_kv_heads == 0 (got H={h}, Hkv={hkv})"
        )
    return jnp.repeat(kv, h // hkv, axis=2)


def attention_reference(
    q, k, v, lengths=None, scale: Optional[float] = None, causal: bool = False
):
    """Dense softmax attention oracle. q [B, L, H, D], k/v [B, L, Hkv, D]
    with Hkv == H (MHA) or H % Hkv == 0 (GQA/MQA: each K/V head serves
    H/Hkv query heads) -> [B, L, H, D]. ``causal`` masks keys after each
    query position (decoder/LM attention)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    k, v = _expand_kv(q, k), _expand_kv(q, v)
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) * scale
    if lengths is not None:
        valid = jnp.arange(k.shape[1])[None, :] < lengths[:, None]  # [B, M]
        scores = jnp.where(valid[:, None, None, :], scores, _NEG)
    if causal:
        l, m = q.shape[1], k.shape[1]
        tri = jnp.arange(m)[None, :] <= jnp.arange(l)[:, None]    # [L, M]
        scores = jnp.where(tri[None, None, :, :], scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v.astype(jnp.float32)).astype(q.dtype)


def _ring_attention_local(
    q, k, v, lengths, scale: float, axis_name: str, causal: bool = False
):
    """Per-device body (inside shard_map): q,k,v are the local sequence
    chunks [B, Lc, H, D]; K/V rotate one neighbor per step."""
    p = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, lc, h, d = q.shape
    positions = jnp.arange(lc)

    def accumulate(step_i, k_blk, v_blk, m, l, o):
        # GQA: the rotating blocks carry only Hkv heads (comm-optimal);
        # repeat to H locally — XLA fuses the broadcast into the einsum
        scores = (
            jnp.einsum("blhd,bmhd->bhlm", q, _expand_kv(q, k_blk)).astype(
                jnp.float32
            )
            * scale
        )  # [B, H, Lc, Lk]
        # the block arriving at ring step s originated on device
        # (idx - s) mod p: its keys cover global positions src*Lc + j
        src = jax.lax.rem(idx - step_i + p, p)
        key_pos = src * lc + positions                        # [Lk]
        if lengths is not None:
            valid = key_pos[None, :] < lengths[:, None]       # [B, Lk]
            scores = jnp.where(valid[:, None, None, :], scores, _NEG)
        if causal:
            # mask by GLOBAL positions: this device's queries sit at
            # idx*Lc + i; a fully-future block masks to _NEG everywhere
            # and contributes ~0 mass (the m0=-1e30 floor keeps the
            # online softmax finite)
            q_pos = idx * lc + positions                      # [Lq]
            tri = key_pos[None, :] <= q_pos[:, None]          # [Lq, Lk]
            scores = jnp.where(tri[None, None, :, :], scores, _NEG)
        blk_max = scores.max(axis=-1)                         # [B, H, Lc]
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)                             # rescale old sums
        probs = jnp.exp(scores - new_m[..., None])            # [B, H, Lc, Lk]
        l = l * corr + probs.sum(axis=-1)
        upd = jnp.einsum(
            "bhlm,bmhd->blhd", probs, _expand_kv(q, v_blk).astype(jnp.float32)
        )
        o = o * corr.transpose(0, 2, 1)[..., None] + upd
        return new_m, l, o

    # Accumulators are per-device state: derive them from q so they carry
    # exactly q's varying axes (seq, and data when the batch is sharded) —
    # a fresh constant would mismatch the fori_loop carry type.
    zero_bhl = jnp.moveaxis(q[..., 0], 1, 2).astype(jnp.float32) * 0.0  # [B,H,Lc]
    m0 = zero_bhl + _NEG
    l0 = zero_bhl
    o0 = q.astype(jnp.float32) * 0.0
    perm = [(j, (j + 1) % p) for j in range(p)]

    def step(i, carry):
        k_blk, v_blk, m, l, o = carry
        m, l, o = accumulate(i, k_blk, v_blk, m, l, o)
        # rotate K/V one neighbor around the ring (ICI hop); runs only for
        # the first p-1 blocks — the last block needs no outgoing hop
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    k_blk, v_blk, m, l, o = jax.lax.fori_loop(0, p - 1, step, (k, v, m0, l0, o0))
    _, l, o = accumulate(p - 1, k_blk, v_blk, m, l, o)
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _shard_map_attention(
    local_fn, q, k, v, mesh, seq_axis, data_axis, lengths, scale, causal=False
):
    """Shared dispatch for both SP flavors: one shard_map over the sequence
    axis (batch optionally on ``data_axis`` — an unsharded spec on a sharded
    batch would silently gather it to every device), ``lengths`` riding
    along per-batch when given."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    spec = P(data_axis, seq_axis, None, None)
    if lengths is None:
        fn = jax.shard_map(
            functools.partial(
                local_fn, lengths=None, scale=scale, axis_name=seq_axis,
                causal=causal,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
        return fn(q, k, v)
    fn = jax.shard_map(
        functools.partial(
            local_fn, scale=scale, axis_name=seq_axis, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, P(data_axis)),
        out_specs=spec,
    )
    return fn(q, k, v, lengths)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    data_axis: Optional[str] = None,
    lengths: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    causal: bool = False,
) -> jax.Array:
    """Exact attention over a sequence sharded on ``mesh[seq_axis]``.

    q: [B, L, H, D]; k,v: [B, L, Hkv, D] with Hkv == H (MHA) or any
    positive divisor of H (GQA/MQA — only the Hkv heads rotate the ring,
    the group repeat fuses locally). L divisible by the axis size. Pass
    ``data_axis`` to keep the batch dim sharded. ``lengths`` [B] masks
    padded key positions (the ingest layer's ``<name>_len`` output).
    """
    return _shard_map_attention(
        _ring_attention_local, q, k, v, mesh, seq_axis, data_axis, lengths,
        scale, causal,
    )


def _ulysses_attention_local(
    q, k, v, lengths, scale: float, axis_name: str, causal: bool = False
):
    """Per-device body (inside shard_map): q,k,v are the local sequence
    chunks [B, Lc, H, D]. Two all-to-alls re-shard sequence<->heads; the
    attention itself is plain dense math over the full sequence for this
    device's H/p head group."""
    # [B, Lc, H, D] -> [B, L, H/p, D]: every device sends each peer its
    # chunk of that peer's head group — one tiled all_to_all on the ICI
    qh, kh, vh = (
        jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)
        for x in (q, k, v)
    )
    # post-exchange each device holds the FULL sequence for its head
    # group, so the dense oracle's local causal mask IS the global one
    out = attention_reference(qh, kh, vh, lengths=lengths, scale=scale, causal=causal)
    # inverse exchange: [B, L, H/p, D] -> [B, Lc, H, D]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    data_axis: Optional[str] = None,
    lengths: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    causal: bool = False,
) -> jax.Array:
    """Exact attention over a sequence sharded on ``mesh[seq_axis]`` via the
    all-to-all (DeepSpeed-Ulysses) pattern — same contract and results as
    :func:`ring_attention`, different collective/memory profile (see module
    docstring for when to pick which).

    q: [B, L, H, D]; k,v: [B, L, Hkv, D] (GQA: Hkv a positive divisor of
    H). L, H, AND Hkv must all be divisible by the axis size — each device
    owns a head group while attending over the full sequence, so MQA
    (Hkv=1) on a >1 axis is ring-only. ``lengths`` [B] masks padded key
    positions.
    """
    p = mesh.shape[seq_axis]
    h, hkv = q.shape[2], k.shape[2]
    if h % p or hkv % p:
        raise ValueError(
            f"ulysses_attention needs num_heads % mesh['{seq_axis}'] == 0 "
            f"for q AND k/v (got H={h}, Hkv={hkv}, axis size {p}); use "
            f"ring_attention when heads cannot cover the sequence axis"
        )
    # H % Hkv is guarded once, in _expand_kv (shared with the ring flavor)
    return _shard_map_attention(
        _ulysses_attention_local, q, k, v, mesh, seq_axis, data_axis, lengths,
        scale, causal,
    )
