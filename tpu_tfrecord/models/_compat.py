"""JAX API compatibility for the model-parallel layer.

The mesh/collective surface these models sit on moved between JAX
releases: ``shard_map`` graduated from ``jax.experimental`` to ``jax.shard_map``
and ``jax.lax.axis_size`` appeared alongside it. The toolchain this repo
pins (jax 0.4.37) predates both — every sharded model path died with
``AttributeError: module 'jax' has no attribute 'shard_map'`` — so the one
resolution lives here and the model files import it instead of guessing.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.6 style
    shard_map = jax.shard_map
except AttributeError:  # the long-lived experimental home
    import functools as _functools

    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

    # The experimental checker mis-types lax.cond branches under grad
    # ("branches of cond produced mismatched replication types" — the
    # zigzag ring's half-block cond); its own error message prescribes
    # check_rep=False, which only disables the static replication CHECK,
    # not any collective the program actually runs.
    shard_map = _functools.partial(_shard_map, check_rep=False)


def axis_size(name: str) -> int:
    """Size of mesh axis ``name`` from inside a shard_map body.

    ``jax.lax.axis_size`` where it exists; otherwise ``psum(1, name)``,
    which jax constant-folds to the axis size at trace time (no runtime
    collective is emitted for a literal operand).
    """
    try:
        return jax.lax.axis_size(name)
    except AttributeError:
        return jax.lax.psum(1, name)
