"""Long-document classifier: the long-context consumer of the ingest layer.

The second model family next to DLRM (models/dlrm.py): where DLRM exercises
dp x tp over tabular Examples, this transformer-style encoder exercises
dp x SP over SequenceExamples — the padded ``frames`` [B, L, D] +
``frames_len`` [B] arrays that `tpu_tfrecord.tpu.ingest` produces from
ragged FeatureLists feed straight into ring attention
(models/attention.py) with the sequence dim sharded on the mesh 'seq'
axis: no device ever holds more than its L/P chunk of K/V, K/V blocks
rotate over ICI, and padded positions are masked exactly via the lengths
the decoder emitted.

TPU shaping: all compute is batched matmuls (MXU) in bfloat16 with float32
accumulation; the train step is one jit (loss -> grad -> optax update,
donated state); no data-dependent Python control flow.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_tfrecord.models import moe as _moe
from tpu_tfrecord.models.attention import (
    attention_reference,
    ring_attention,
    ulysses_attention,
)
from tpu_tfrecord.models.dlrm import (
    _dense_init as _dlrm_dense_init,
    batch_shardings as _dlrm_batch_shardings,
)


@dataclass(frozen=True)
class LongDocConfig:
    seq_dim: int = 16        # input frame feature dim (ingest output)
    d_model: int = 32
    n_heads: int = 4
    # 0 = MHA (n_kv_heads == n_heads). Set lower for GQA/MQA: k/v carry
    # only this many heads — smaller qkv projection AND smaller K/V blocks
    # on the SP collectives (ring rotations / ulysses exchanges move Hkv,
    # not H) — each serving n_heads/n_kv_heads query heads. NOTE the
    # ulysses flavor additionally needs n_kv_heads % seq-axis size == 0
    # (it splits kv heads across the axis), so MQA (1 kv head) on a >1
    # seq axis is ring-only.
    n_kv_heads: int = 0
    n_layers: int = 2
    mlp_mult: int = 4
    n_classes: int = 2
    max_len: int = 128       # padded sequence length (pad_to of the ingest)
    dtype: Any = jnp.bfloat16
    # sequence-parallel attention flavor when a mesh is given: 'ring'
    # (ppermute K/V rotation — any head count, O(Lc^2) memory) or
    # 'ulysses' (2 all_to_alls + dense per head group — needs
    # n_heads % seq_axis_size == 0; fewer collective hops at moderate L)
    sp_attention: str = "ring"
    # rematerialize each block in backward (jax.checkpoint): activation
    # memory drops from O(n_layers * L) to O(L) at ~1.3x backward FLOPs —
    # the standard long-context trade when L is large
    remat: bool = False
    # > 0 swaps every block's dense FFN for a Switch-style MoE with this
    # many experts (models.moe; d_ff = mlp_mult * d_model per expert). The
    # load-balance aux losses accumulate across layers and join the
    # objective scaled by moe_aux_weight. Expert weights live at
    # params['layers'][i]['moe'] — place them on a mesh axis with
    # moe.param_shardings for EP.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01


def _dense_init(rng, fan_in: int, fan_out: int):
    # gain=1: pre-norm residual blocks want unit-variance projections
    return _dlrm_dense_init(rng, fan_in, fan_out, gain=1.0)


def init_params(rng: jax.Array, cfg: LongDocConfig) -> Dict[str, Any]:
    if cfg.d_model % cfg.n_heads:
        raise ValueError(
            f"n_heads ({cfg.n_heads}) must divide d_model ({cfg.d_model}) evenly"
        )
    if cfg.sp_attention not in ("ring", "ulysses"):
        raise ValueError(
            f"sp_attention must be 'ring' or 'ulysses', got {cfg.sp_attention!r}"
        )
    hkv = cfg.n_kv_heads or cfg.n_heads
    if hkv <= 0 or cfg.n_heads % hkv:
        raise ValueError(
            f"n_kv_heads must be a positive divisor of n_heads "
            f"({cfg.n_heads}); got {cfg.n_kv_heads}"
        )
    keys = jax.random.split(rng, 3 + cfg.n_layers)
    params: Dict[str, Any] = {
        "embed": _dense_init(keys[0], cfg.seq_dim, cfg.d_model),
        # learned positions: [max_len, d_model]
        "pos": jax.random.normal(keys[1], (cfg.max_len, cfg.d_model), jnp.float32)
        * 0.02,
        "head": _dense_init(keys[2], cfg.d_model, cfg.n_classes),
    }
    layers = []
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[3 + i], 4)
        dh = cfg.d_model // cfg.n_heads
        layer = {
            # q gets H heads, k and v get Hkv each (== 3*d_model for MHA)
            "qkv": _dense_init(
                k[0], cfg.d_model, (cfg.n_heads + 2 * hkv) * dh
            ),
            "proj": _dense_init(k[1], cfg.d_model, cfg.d_model),
        }
        if cfg.moe_experts > 0:
            layer["moe"] = _moe.init_params(k[2], _moe_cfg(cfg))
        else:
            layer["mlp_in"] = _dense_init(
                k[2], cfg.d_model, cfg.mlp_mult * cfg.d_model
            )
            layer["mlp_out"] = _dense_init(
                k[3], cfg.mlp_mult * cfg.d_model, cfg.d_model
            )
        layers.append(layer)
    params["layers"] = layers
    return params


def _moe_cfg(cfg: LongDocConfig) -> "_moe.MoEConfig":
    return _moe.MoEConfig(
        d_model=cfg.d_model,
        d_ff=cfg.mlp_mult * cfg.d_model,
        n_experts=cfg.moe_experts,
        capacity_factor=cfg.moe_capacity_factor,
        dtype=cfg.dtype,
    )


def _dense(layer, x, dt):
    return x @ layer["w"].astype(dt) + layer["b"].astype(dt)


def _rms_norm(x):
    scale = jax.lax.rsqrt(
        jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True) + 1e-6
    )
    return (x.astype(jnp.float32) * scale).astype(x.dtype)


def forward(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: LongDocConfig,
    mesh: Optional[Mesh] = None,
    seq_axis: str = "seq",
    data_axis: Optional[str] = None,
    with_aux: bool = False,
) -> Union[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Logits [B, n_classes]. With ``mesh``, attention runs sequence-
    parallel over ``seq_axis`` in the flavor ``cfg.sp_attention`` selects
    ('ring': ppermute K/V rotation, any head count; 'ulysses': 2
    all_to_alls, needs n_heads % seq-axis size == 0); without a mesh, the
    dense reference. All flavors are numerically equivalent (pinned by
    tests).

    ``with_aux=True`` returns (logits, aux) where aux is the summed MoE
    load-balance loss across layers (0.0 for the dense FFN) — loss_fn
    uses it; plain callers keep the logits-only signature."""
    dt = cfg.dtype
    frames = batch["frames"].astype(dt)                    # [B, L, Din]
    lengths = batch["frames_len"]
    b, l, _ = frames.shape
    h = cfg.n_heads
    hkv = cfg.n_kv_heads or h
    dh = cfg.d_model // h
    x = _dense(params["embed"], frames, dt) + params["pos"][:l].astype(dt)[None]
    # one validity mask for BOTH expert routing and the final pooling, so
    # the two inertness contracts can never desynchronize
    valid = jnp.arange(l)[None, :] < lengths[:, None]          # [B, L]

    def block(x, layer):
        qkv = _dense(layer["qkv"], _rms_norm(x), dt)   # [B, L, (H+2*Hkv)*dh]
        q, k, v = jnp.split(
            qkv, [h * dh, (h + hkv) * dh], axis=-1
        )
        q = q.reshape(b, l, h, dh)
        k = k.reshape(b, l, hkv, dh)
        v = v.reshape(b, l, hkv, dh)
        if mesh is not None:
            if cfg.sp_attention == "ulysses":
                sp = ulysses_attention
            elif cfg.sp_attention == "ring":
                sp = ring_attention
            else:  # a config mutated after init_params must not silently
                raise ValueError(  # run a different collective pattern
                    f"sp_attention must be 'ring' or 'ulysses', got "
                    f"{cfg.sp_attention!r}"
                )
            att = sp(
                q, k, v, mesh, seq_axis=seq_axis, data_axis=data_axis,
                lengths=lengths,
            )
        else:
            att = attention_reference(q, k, v, lengths=lengths)
        x = x + _dense(layer["proj"], att.reshape(b, l, cfg.d_model), dt)
        if cfg.moe_experts > 0:
            # padding positions are masked OUT of routing, capacity, and
            # the aux loss — logits must depend only on valid content
            # (same inertness contract as the attention mask)
            y, aux = _moe.moe_apply(
                layer["moe"], _rms_norm(x), _moe_cfg(cfg), valid=valid
            )
            return x + y, aux  # dropped tokens ride this residual
        y = _dense(layer["mlp_in"], _rms_norm(x), dt)
        return x + _dense(layer["mlp_out"], jax.nn.gelu(y), dt), jnp.float32(0.0)

    if cfg.remat:
        block = jax.checkpoint(block)
    aux_total = jnp.float32(0.0)
    for layer in params["layers"]:
        x, aux = block(x, layer)
        aux_total = aux_total + aux
    # masked mean pool over the valid prefix
    mask = valid.astype(jnp.float32)
    pooled = (x.astype(jnp.float32) * mask[:, :, None]).sum(axis=1) / jnp.maximum(
        mask.sum(axis=1, keepdims=True), 1.0
    )
    logits = _dense(params["head"], pooled.astype(dt), dt).astype(jnp.float32)
    return (logits, aux_total) if with_aux else logits


def loss_fn(params, batch, cfg: LongDocConfig, mesh=None, seq_axis="seq",
            data_axis=None) -> jax.Array:
    logits, aux = forward(
        params, batch, cfg, mesh, seq_axis, data_axis, with_aux=True
    )
    labels = batch["label"].astype(jnp.int32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ce = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
    return ce + cfg.moe_aux_weight * aux


def train_step(params, opt_state, batch, cfg: LongDocConfig, tx, mesh=None,
               seq_axis="seq", data_axis=None):
    """One optimizer step; jit this whole function (mesh static via closure)."""
    loss, grads = jax.value_and_grad(loss_fn)(
        params, batch, cfg, mesh, seq_axis, data_axis
    )
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree.map(lambda p, u: p + u, params, updates)
    return params, opt_state, loss


def batch_shardings(mesh: Mesh, batch, data_axis: str = "data",
                    seq_axis: Optional[str] = "seq"):
    """Same policy as dlrm.batch_shardings ('frames' on (data, seq), batch
    dim everywhere else), with SP on by default for this family."""
    return _dlrm_batch_shardings(mesh, batch, data_axis=data_axis, seq_axis=seq_axis)


def make_synthetic_batch(cfg: LongDocConfig, batch_size: int, seed: int = 0):
    """Host batch in the ingest layer's layout (frames/frames_len/label).
    Labels correlate with the frames so training has signal."""
    rng = np.random.default_rng(seed)
    frames = rng.normal(size=(batch_size, cfg.max_len, cfg.seq_dim)).astype(
        np.float32
    )
    lengths = rng.integers(1, cfg.max_len + 1, size=(batch_size,)).astype(np.int32)
    mask = np.arange(cfg.max_len)[None, :] < lengths[:, None]
    mean0 = (frames[:, :, 0] * mask).sum(axis=1) / np.maximum(mask.sum(axis=1), 1)
    label = (mean0 > 0).astype(np.int32) % cfg.n_classes
    return {"frames": frames, "frames_len": lengths, "label": label}


def param_shardings(mesh: Mesh, params):
    """Replicated parameters (the model is small; SP shards activations)."""
    return jax.tree.map(lambda _: NamedSharding(mesh, P()), params)
