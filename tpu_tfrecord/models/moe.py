"""Mixture-of-Experts layer with expert parallelism (EP) over a mesh axis.

The reference framework ships no model code (SURVEY.md §2: parallelism rows
beyond DP are N/A) — this is the EP member of the consumer-model family
that exercises the ingestion pipeline under every parallelism style the
mesh supports (dp/tp/sp are covered by models.dlrm and models.attention;
pp by models.pipeline).

TPU-first construction (the Switch-Transformer / Mesh-TensorFlow dispatch
formulation, arXiv:2101.03961 §2.2):
- top-1 routing with a FIXED per-expert capacity: every tensor keeps a
  static shape, so the whole layer jits once and lands on the MXU as three
  einsums (dispatch, expert FFN, combine) — no gather/scatter with
  data-dependent shapes, no host round trips.
- dispatch/combine are one-hot einsums: tokens beyond an expert's capacity
  contribute zero to the combine (dropped tokens ride the residual
  connection — exactly the Switch behavior).
- EP = the expert-indexed [E, ...] tensors sharded over a mesh axis via
  NamedSharding; under jit, XLA inserts the collectives that move tokens
  between the data and expert shardings per its cost model (all-to-all on
  pod shapes, gather/reduce on small ones) — the role the torch
  implementations hand-roll with NCCL alltoall. Expert weights never
  replicate; that is what makes it EP.
- the router adds the standard load-balance auxiliary loss (mean fraction
  * mean router prob per expert, scaled by E) so training spreads tokens.

`moe_apply` is the layer; `moe_reference` is the per-token oracle used by
the tests; `param_shardings` places the expert tensors on the EP axis.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MoEConfig:
    d_model: int = 32
    d_ff: int = 64          # per-expert hidden width
    n_experts: int = 4
    # capacity = ceil(tokens/expert * factor); 1.0 = perfectly balanced
    # routing just fits, >1 gives slack before drops (Switch default 1.25)
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32


def init_params(rng: jax.Array, cfg: MoEConfig) -> Dict[str, Any]:
    kr, k1, k2 = jax.random.split(rng, 3)
    scale_in = (2.0 / cfg.d_model) ** 0.5
    scale_out = (2.0 / cfg.d_ff) ** 0.5
    return {
        "router": jax.random.normal(kr, (cfg.d_model, cfg.n_experts)) * 0.02,
        # expert-stacked FFN weights: [E, ...] is the EP-sharded dim
        "w_in": jax.random.normal(k1, (cfg.n_experts, cfg.d_model, cfg.d_ff))
        * scale_in,
        "w_out": jax.random.normal(k2, (cfg.n_experts, cfg.d_ff, cfg.d_model))
        * scale_out,
    }


def param_shardings(mesh: Mesh, expert_axis: str = "model") -> Dict[str, Any]:
    """NamedShardings placing the expert dim on ``expert_axis`` (router
    replicated). Apply with jax.device_put / as jit out_shardings."""
    return {
        "router": NamedSharding(mesh, P()),
        "w_in": NamedSharding(mesh, P(expert_axis, None, None)),
        "w_out": NamedSharding(mesh, P(expert_axis, None, None)),
    }


def _capacity(tokens: int, cfg: MoEConfig) -> int:
    # ceil, per the config contract: factor 1.0 must JUST FIT perfectly
    # balanced routing (floor would drop tokens even when balanced).
    #
    # ``tokens`` is the STATIC flattened count INCLUDING padding, even
    # when ``moe_apply`` is given a ``valid`` mask (ADVICE r5 #3 — a
    # deliberate choice, documented here): capacity must be a
    # compile-time constant for the static-shape dispatch/combine
    # einsums, and the valid-token count is a runtime value. The effect
    # is CONSERVATIVE relative to the Switch formulation on heavily
    # padded batches — effective capacity_factor over valid tokens is
    # inflated, so FEWER tokens drop than factor implies, at the cost of
    # dispatch/combine tensors sized for the padded length. Callers
    # wanting a tighter match can shrink capacity_factor by their static
    # worst-case valid fraction.
    cap = -(-int(tokens * cfg.capacity_factor) // cfg.n_experts)
    return max(1, cap)


def moe_apply(
    params: Dict[str, Any],
    x: jax.Array,
    cfg: MoEConfig,
    valid: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-1 MoE FFN. x: [..., T, D] (leading dims flattened internally).
    Returns (y, aux_loss) with y.shape == x.shape; dropped tokens yield 0
    (add the residual outside). All shapes static — jits once.

    ``valid``: optional boolean mask shaped like x without the feature dim
    ([..., T]). Invalid (padding) tokens are excluded ENTIRELY: they get
    zero output, consume no expert capacity (cannot displace later valid
    tokens), and contribute nothing to the aux loss — so results depend
    only on valid positions' content.
    """
    orig_shape = x.shape
    d = orig_shape[-1]
    xt = x.reshape(-1, d)                                     # [T, D]
    t = xt.shape[0]
    e = cfg.n_experts
    c = _capacity(t, cfg)

    logits = xt.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                    # [T, E]
    expert = jnp.argmax(probs, axis=-1)                        # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]  # [T]

    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)      # [T, E]
    if valid is not None:
        vt = valid.reshape(-1).astype(jnp.float32)             # [T]
        onehot = onehot * vt[:, None]   # padding: no expert, no capacity
        gate = gate * vt
        n_tokens = jnp.maximum(vt.sum(), 1.0)
        probs_for_aux = probs * vt[:, None]
    else:
        n_tokens = jnp.float32(t)
        probs_for_aux = probs
    # position of each token within its expert's queue (0-based)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0            # [T, E]
    kept = (pos < c) & (onehot > 0)                            # [T, E]
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), c, dtype=jnp.float32)
    dispatch = jnp.where(kept[..., None], pos_oh, 0.0)         # [T, E, C]
    combine = dispatch * gate[:, None, None]                   # [T, E, C]

    # load-balance aux loss (Switch eq. 4): E * mean(frac_tokens * mean_prob)
    # — means over VALID tokens only
    frac = onehot.sum(axis=0) / n_tokens                       # [E]
    mean_prob = probs_for_aux.sum(axis=0) / n_tokens           # [E]
    aux = (frac * mean_prob).sum() * e

    dt = cfg.dtype
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dt), xt.astype(dt))
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"].astype(dt)))
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"].astype(dt))
    y = jnp.einsum("tec,ecd->td", combine.astype(dt), expert_out)
    return y.reshape(orig_shape).astype(x.dtype), aux.astype(jnp.float32)


def moe_reference(
    params: Dict[str, Any],
    x: jax.Array,
    cfg: MoEConfig,
    valid: Optional[Any] = None,
) -> jax.Array:
    """Per-token oracle: route each token to its argmax expert's FFN, gate
    by the router prob, drop tokens beyond capacity in arrival order;
    invalid tokens (``valid`` mask) are skipped entirely —
    definitionally what moe_apply's einsum dance computes."""
    import numpy as np

    xt = np.asarray(x, dtype=np.float64).reshape(-1, x.shape[-1])
    vmask = (
        np.asarray(valid).reshape(-1) if valid is not None
        else np.ones(xt.shape[0], dtype=bool)
    )
    router = np.asarray(params["router"], dtype=np.float64)
    w_in = np.asarray(params["w_in"], dtype=np.float64)
    w_out = np.asarray(params["w_out"], dtype=np.float64)
    t = xt.shape[0]
    cap = _capacity(t, cfg)
    logits = xt @ router
    z = np.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = z / z.sum(axis=-1, keepdims=True)
    expert = probs.argmax(axis=-1)
    counts = {ei: 0 for ei in range(cfg.n_experts)}
    out = np.zeros_like(xt)
    for i in range(t):
        if not vmask[i]:
            continue
        ei = int(expert[i])
        if counts[ei] >= cap:
            continue
        counts[ei] += 1
        h = xt[i] @ w_in[ei]
        h = 0.5 * h * (1.0 + np.tanh(np.sqrt(2 / np.pi) * (h + 0.044715 * h**3)))
        out[i] = probs[i, ei] * (h @ w_out[ei])
    return out.reshape(x.shape)
