"""Mixture-of-Experts layer with expert parallelism (EP) over a mesh axis.

The reference framework ships no model code (SURVEY.md §2: parallelism rows
beyond DP are N/A) — this is the EP member of the consumer-model family
that exercises the ingestion pipeline under every parallelism style the
mesh supports (dp/tp/sp are covered by models.dlrm and models.attention;
pp by models.pipeline).

TPU-first construction (the Switch-Transformer / Mesh-TensorFlow dispatch
formulation, arXiv:2101.03961 §2.2, top-k per GShard arXiv:2006.16668):
- top-k routing (k=1 Switch default, k=2 the GShard/LM default) with a
  FIXED per-expert capacity: every tensor keeps a static shape, so the
  whole layer jits once and lands on the MXU as three einsums (dispatch,
  expert FFN, combine) — no gather/scatter with data-dependent shapes, no
  host round trips.
- dispatch/combine are one-hot einsums: tokens beyond an expert's capacity
  contribute zero to the combine (dropped tokens ride the residual
  connection — exactly the Switch behavior). Arrival order is rank-major:
  every rank-0 (first-choice) assignment queues before any rank-1
  assignment, then token order within a rank — the GShard "second-place
  experts ride behind first-place" rule. Combine gates are the RAW router
  probabilities of each chosen expert (no top-k renormalization), so
  ``top_k=1`` reproduces the original Switch layer bit-for-bit.
- two EP flavors:
  * `moe_apply` — the auto-sharded layer: expert-indexed [E, ...] tensors
    carry NamedShardings and XLA inserts whatever collectives its cost
    model picks. Composable anywhere (models.long_doc uses it), but the
    collective pattern is XLA's choice, not a contract.
  * `moe_apply_ep` — the comms-PINNED layer: an explicit `shard_map` over
    the expert axis with the token stream sharded on the same axis. Each
    device routes its own tokens, `lax.all_to_all` exchanges the
    dispatched capacity slices so every device runs ONLY its E/P experts,
    and the inverse all_to_all brings expert outputs home for the local
    combine. The compiled HLO contains `all-to-all` and NO `all-gather`
    of tokens or expert weights — asserted by tests/hlo_util, the
    contract `moe_apply` claims but cannot pin. Capacity is per
    (expert, token-shard): each shard applies its own ceil(Tl·cf·k/E)
    budget — the real distributed Switch semantics, mirrored exactly by
    ``moe_reference(shards=P)``. Its per-device body is exposed as
    `moe_ep_body` so EP composes under an ENCLOSING shard_map — the
    interleaved pipeline (models.pipeline, ``param_spec``) runs it as a
    virtual-stage chunk on a pipe×expert mesh, all-to-all intact.
- the router adds the standard load-balance auxiliary loss (mean fraction
  of FIRST-choice assignments * mean router prob per expert, scaled by E)
  so training spreads tokens.

`moe_reference` is the per-token oracle used by the tests;
`param_shardings` places the expert tensors on the EP axis.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_tfrecord.models._compat import shard_map


@dataclass(frozen=True)
class MoEConfig:
    d_model: int = 32
    d_ff: int = 64          # per-expert hidden width
    n_experts: int = 4
    # capacity = ceil(tokens * factor * top_k / n_experts); 1.0 =
    # perfectly balanced routing just fits, >1 gives slack before drops
    # (Switch default 1.25)
    capacity_factor: float = 1.25
    # experts per token: 1 = Switch, 2 = GShard-style top-2 (second choice
    # queues behind every first choice; raw-prob gates, no renorm)
    top_k: int = 1
    dtype: Any = jnp.float32


def init_params(rng: jax.Array, cfg: MoEConfig) -> Dict[str, Any]:
    kr, k1, k2 = jax.random.split(rng, 3)
    scale_in = (2.0 / cfg.d_model) ** 0.5
    scale_out = (2.0 / cfg.d_ff) ** 0.5
    return {
        "router": jax.random.normal(kr, (cfg.d_model, cfg.n_experts)) * 0.02,
        # expert-stacked FFN weights: [E, ...] is the EP-sharded dim
        "w_in": jax.random.normal(k1, (cfg.n_experts, cfg.d_model, cfg.d_ff))
        * scale_in,
        "w_out": jax.random.normal(k2, (cfg.n_experts, cfg.d_ff, cfg.d_model))
        * scale_out,
    }


def param_shardings(mesh: Mesh, expert_axis: str = "model") -> Dict[str, Any]:
    """NamedShardings placing the expert dim on ``expert_axis`` (router
    replicated). Apply with jax.device_put / as jit out_shardings."""
    return {
        "router": NamedSharding(mesh, P()),
        "w_in": NamedSharding(mesh, P(expert_axis, None, None)),
        "w_out": NamedSharding(mesh, P(expert_axis, None, None)),
    }


def _capacity(tokens: int, cfg: MoEConfig) -> int:
    # ceil, per the config contract: factor 1.0 must JUST FIT perfectly
    # balanced routing (floor would drop tokens even when balanced); the
    # top_k assignments per token scale the budget the same way GShard's
    # 2N/E does.
    #
    # ``tokens`` is the STATIC flattened count INCLUDING padding, even
    # when ``moe_apply`` is given a ``valid`` mask (ADVICE r5 #3 — a
    # deliberate choice, documented here): capacity must be a
    # compile-time constant for the static-shape dispatch/combine
    # einsums, and the valid-token count is a runtime value. The effect
    # is CONSERVATIVE relative to the Switch formulation on heavily
    # padded batches — effective capacity_factor over valid tokens is
    # inflated, so FEWER tokens drop than factor implies, at the cost of
    # dispatch/combine tensors sized for the padded length. Callers
    # wanting a tighter match can shrink capacity_factor by their static
    # worst-case valid fraction. Under ``moe_apply_ep`` the count is the
    # per-shard token count: capacity is a per-(expert, shard) budget.
    cap = -(-int(tokens * cfg.capacity_factor) * cfg.top_k // cfg.n_experts)
    return max(1, cap)


def _route(probs: jax.Array, cfg: MoEConfig, c: int,
           valid: Optional[jax.Array] = None):
    """Shared top-k routing: probs [T, E] -> (dispatch [T, E, C],
    combine [T, E, C], onehot0 [T, E] first-choice assignment,
    routed [E] total assignments per expert across all ranks — the
    diagnostics' "tokens routed" count, kept or dropped — and kept [E],
    the assignments that won a capacity slot. kept is summed from the
    per-rank [T, E] masks here, NOT from the [T, E, C] dispatch tensor:
    a dispatch.sum would force that tensor to materialize instead of
    fusing into the dispatch einsum (measured at ~6% step overhead);
    unused outputs cost nothing — XLA DCEs them when diagnostics is off.

    Arrival order is rank-major (all rank-0 choices in token order, then
    rank-1, ...): rank-k queue positions start after every lower rank's
    TOTAL per-expert assignment count, so a flood of first choices can
    push second choices past capacity but never vice versa."""
    e = cfg.n_experts
    if not (1 <= cfg.top_k <= e):
        raise ValueError(
            f"top_k must be in [1, n_experts={e}], got {cfg.top_k}"
        )
    masked = probs
    prev_total = jnp.zeros((e,), jnp.float32)
    kept_total = jnp.zeros((e,), jnp.float32)
    dispatch = jnp.zeros(probs.shape + (c,), jnp.float32)
    combine = jnp.zeros(probs.shape + (c,), jnp.float32)
    onehot0 = None
    for _ in range(cfg.top_k):
        expert = jnp.argmax(masked, axis=-1)                    # [T]
        gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)   # [T, E]
        if valid is not None:
            onehot = onehot * valid[:, None]  # padding: no expert, no slot
            gate = gate * valid
        # position of each token within its expert's queue (0-based),
        # continuing after every lower rank's arrivals
        pos = (jnp.cumsum(onehot, axis=0) - 1.0 + prev_total[None, :]) * onehot
        kept = (pos < c) & (onehot > 0)                         # [T, E]
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), c, dtype=jnp.float32)
        d_k = jnp.where(kept[..., None], pos_oh, 0.0)           # [T, E, C]
        dispatch = dispatch + d_k
        combine = combine + d_k * gate[:, None, None]
        if onehot0 is None:
            onehot0 = onehot
        prev_total = prev_total + onehot.sum(axis=0)
        kept_total = kept_total + kept.astype(jnp.float32).sum(axis=0)
        # exclude this rank's pick from the next argmax
        masked = jnp.where(onehot > 0, -jnp.inf, masked)
    return dispatch, combine, onehot0, prev_total, kept_total


def _expert_ffn(params: Dict[str, Any], expert_in: jax.Array, dt) -> jax.Array:
    """[E, C, D] -> [E, C, D] through each expert's gelu FFN (einsum dims
    are expert-local, so the same code serves the dense and EP bodies)."""
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"].astype(dt))
    )
    return jnp.einsum("ecf,efd->ecd", h, params["w_out"].astype(dt))


def _moe_local(params, xt, cfg: MoEConfig, valid_flat, *, c: int,
               exchange=None, diagnostics: bool = False):
    """Route + dispatch + FFN + combine over ONE token shard — the ONE
    per-shard body both flavors share. Returns (y [T, D], aux numerator
    pieces, diag numerator pieces or None): the caller owns how the
    aux-loss/diagnostic sums reduce (locally for the dense layer, psum
    for the EP layer). ``exchange`` is an optional (to_experts,
    from_experts) pair wrapped around the expert FFN — identity for the
    dense layer, the all_to_all pair for EP.

    ``diagnostics`` (a STATIC flag: off-path jits to exactly the old
    program) additionally returns (routed [E] assignments per expert
    across all ranks, kept [E] assignments that won a capacity slot,
    entropy_sum scalar — router-prob entropy summed over valid tokens).
    Every piece is a sum, so cross-shard reduction is one psum."""
    logits = xt.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                    # [T, E]
    dispatch, combine, onehot0, routed, kept = _route(
        probs, cfg, c, valid_flat
    )
    if valid_flat is not None:
        n_tokens = valid_flat.sum()
        probs_for_aux = probs * valid_flat[:, None]
    else:
        n_tokens = jnp.float32(xt.shape[0])
        probs_for_aux = probs
    assign_sum = onehot0.sum(axis=0)                           # [E]
    prob_sum = probs_for_aux.sum(axis=0)                       # [E]
    diag = None
    if diagnostics:
        ent = -(probs * jnp.log(probs + 1e-9)).sum(axis=-1)    # [T]
        if valid_flat is not None:
            ent = ent * valid_flat
        diag = (
            jax.lax.stop_gradient(routed),
            jax.lax.stop_gradient(kept),
            jax.lax.stop_gradient(ent.sum()),
        )

    dt = cfg.dtype
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dt), xt.astype(dt))
    if exchange is not None:
        expert_in = exchange[0](expert_in)
    expert_out = _expert_ffn(params, expert_in, dt)
    if exchange is not None:
        expert_out = exchange[1](expert_out)
    y = jnp.einsum("tec,ecd->td", combine.astype(dt), expert_out)
    return y, (assign_sum, prob_sum, n_tokens), diag


def _aux_loss(assign_sum, prob_sum, n_tokens, e: int) -> jax.Array:
    # load-balance aux loss (Switch eq. 4): E * mean(frac_tokens *
    # mean_prob), fractions over FIRST-choice assignments and VALID tokens
    n = jnp.maximum(n_tokens, 1.0)
    return ((assign_sum / n) * (prob_sum / n)).sum() * e


def _diag_dict(routed, kept, entropy_sum, n_tokens) -> Dict[str, jax.Array]:
    """The diagnostics contract both flavors return (GLOBAL sums for EP —
    the caller psums the pieces before building this):

    - ``expert_tokens`` [E] f32: assignments routed to each expert across
      every rank (kept or dropped) — sums to valid_tokens * top_k.
    - ``expert_kept`` [E] f32: assignments that won a capacity slot.
    - ``dropped_fraction`` scalar: 1 - kept/routed (the Switch overflow
      rate; dropped tokens ride the residual).
    - ``gate_entropy`` scalar: mean router-prob entropy per valid token
      (nats; ln(E) = maximally undecided router, ~0 = collapsed).

    All static-shaped, all stop_gradient'd — reading them costs no
    backward pass and cannot perturb training numerics."""
    routed_total = jnp.maximum(routed.sum(), 1.0)
    return {
        "expert_tokens": routed,
        "expert_kept": kept,
        "dropped_fraction": 1.0 - kept.sum() / routed_total,
        "gate_entropy": entropy_sum / jnp.maximum(n_tokens, 1.0),
    }


def moe_apply(
    params: Dict[str, Any],
    x: jax.Array,
    cfg: MoEConfig,
    valid: Optional[jax.Array] = None,
    diagnostics: bool = False,
):
    """Top-k MoE FFN, auto-sharded flavor. x: [..., T, D] (leading dims
    flattened internally). Returns (y, aux_loss) with y.shape == x.shape;
    dropped tokens yield 0 (add the residual outside). All shapes static —
    jits once. EP comes from `param_shardings` on the [E, ...] tensors;
    the collective pattern is XLA's pick (use `moe_apply_ep` when the
    all-to-all must be a contract).

    ``valid``: optional boolean mask shaped like x without the feature dim
    ([..., T]). Invalid (padding) tokens are excluded ENTIRELY: they get
    zero output, consume no expert capacity (cannot displace later valid
    tokens), and contribute nothing to the aux loss — so results depend
    only on valid positions' content.

    ``diagnostics`` (static flag; False jits the exact pre-flag program)
    returns (y, aux_loss, diag) instead, where diag is the `_diag_dict`
    contract (per-expert routed/kept counts, dropped fraction, gate
    entropy) — pinned against `moe_reference(..., return_diag=True)`.
    """
    orig_shape = x.shape
    d = orig_shape[-1]
    xt = x.reshape(-1, d)                                     # [T, D]
    c = _capacity(xt.shape[0], cfg)
    valid_flat = (
        valid.reshape(-1).astype(jnp.float32) if valid is not None else None
    )
    y, (assign_sum, prob_sum, n_tokens), diag = _moe_local(
        params, xt, cfg, valid_flat, c=c, diagnostics=diagnostics
    )
    aux = _aux_loss(assign_sum, prob_sum, n_tokens, cfg.n_experts)
    y = y.reshape(orig_shape).astype(x.dtype)
    aux = aux.astype(jnp.float32)
    if not diagnostics:
        return y, aux
    return y, aux, _diag_dict(*diag, n_tokens)


def moe_ep_body(
    params_local: Dict[str, Any],
    x_local: jax.Array,
    cfg: MoEConfig,
    expert_axis: str,
    data_axis: Optional[str] = None,
    valid_local: Optional[jax.Array] = None,
    diagnostics: bool = False,
):
    """The per-device EP body — the all-to-all dispatch WITHOUT the
    enclosing shard_map, so EP composes under someone else's manual mesh
    (the interleaved pipeline runs it inside a pipe×V×expert shard_map as
    a virtual-stage chunk; `moe_apply_ep` is this body wrapped in its own
    shard_map).

    Call it only inside a shard_map whose mesh carries ``expert_axis``.
    ``params_local`` holds THIS device's expert shard ([E/P, ...] w_in /
    w_out, replicated router); ``x_local`` is this device's token shard
    [..., T_local, D] (leading dims flattened into the token count, which
    sets the per-shard capacity budget). Returns (y, aux) with y shaped
    like ``x_local`` — or (y, aux, diag) with ``diagnostics``, the
    `_diag_dict` contract psum'd over ``expert_axis`` (+ ``data_axis``)
    so the ratios are global, exactly like `moe_apply_ep`'s."""
    xt = x_local.reshape(-1, x_local.shape[-1])
    vf = (
        valid_local.reshape(-1).astype(jnp.float32)
        if valid_local is not None else None
    )
    c = _capacity(xt.shape[0], cfg)
    # THE exchange around the shared per-shard body: slice the expert
    # dim P ways, every device keeps its E/P experts and receives the
    # matching [E, C, D] capacity slices from all peers (concat on the
    # capacity dim -> [E/P, P*C, D]); the inverse brings expert
    # outputs back to the token-owning device — tokens move, weights
    # never do
    exchange = (
        lambda a: jax.lax.all_to_all(
            a, expert_axis, split_axis=0, concat_axis=1, tiled=True
        ),
        lambda a: jax.lax.all_to_all(
            a, expert_axis, split_axis=1, concat_axis=0, tiled=True
        ),
    )
    y, (assign_sum, prob_sum, n_tok), diag = _moe_local(
        params_local, xt, cfg, vf, c=c, exchange=exchange,
        diagnostics=diagnostics,
    )
    # aux loss over the GLOBAL token stream: tiny [E] reductions
    axes = (expert_axis,) + ((data_axis,) if data_axis else ())
    aux = _aux_loss(
        jax.lax.psum(assign_sum, axes),
        jax.lax.psum(prob_sum, axes),
        jax.lax.psum(n_tok, axes),
        cfg.n_experts,
    )
    out = (
        y.reshape(x_local.shape).astype(x_local.dtype),
        aux.astype(jnp.float32),
    )
    if not diagnostics:
        return out
    routed, kept, ent_sum = diag
    # GLOBAL diagnostics: psum the sums, THEN form the ratios
    return out + (_diag_dict(
        jax.lax.psum(routed, axes),
        jax.lax.psum(kept, axes),
        jax.lax.psum(ent_sum, axes),
        jax.lax.psum(n_tok, axes),
    ),)


def moe_apply_ep(
    params: Dict[str, Any],
    x: jax.Array,
    cfg: MoEConfig,
    mesh: Mesh,
    expert_axis: str = "expert",
    data_axis: Optional[str] = None,
    valid: Optional[jax.Array] = None,
    diagnostics: bool = False,
):
    """Comms-pinned EP flavor: explicit shard_map over ``expert_axis``
    with the TOKEN dim sharded on the same axis.

    x: [..., T, D] with T divisible by the expert-axis size P (and
    n_experts % P == 0). Each device routes its own T/P tokens under a
    per-shard capacity, one `lax.all_to_all` scatters the dispatched
    [E, C, D] capacity slices so device p computes ONLY its E/P experts
    over the P·C slots it received, and the inverse all_to_all returns
    expert outputs for the local combine. Expert weights and tokens never
    gather — per-device memory is the shard (E/P experts + T/P tokens +
    the exchanged capacity slices) and the HLO contains `all-to-all`, no
    `all-gather` (pinned by tests). Pass ``data_axis`` to keep leading
    batch dims sharded as well. Numerics == `moe_reference(shards=P)`.

    ``diagnostics`` (static flag; off = the exact pre-flag program)
    returns (y, aux, diag): every diag piece (routed/kept per expert,
    entropy sum, token count) is psum'd over the expert axis (and
    ``data_axis`` when given) BEFORE the ratios form — a per-shard
    dropped fraction averaged across shards would not equal the global
    overflow rate. Tiny [E]/scalar reductions, same cost class as the
    aux loss.
    """
    p = mesh.shape[expert_axis]
    e = cfg.n_experts
    if e % p:
        raise ValueError(
            f"moe_apply_ep needs n_experts % mesh['{expert_axis}'] == 0 "
            f"(got E={e}, axis size {p})"
        )
    t_dim = x.shape[-2]
    if t_dim % p:
        raise ValueError(
            f"moe_apply_ep needs the token dim % mesh['{expert_axis}'] == 0 "
            f"(got T={t_dim}, axis size {p}); pad or re-bucket the stream"
        )
    # per-shard token count is static inside the body: the local capacity
    # budget (moe_ep_body derives it from its shard's flattened shape)
    lead = x.shape[:-2]
    dp = (data_axis,) if data_axis is not None and lead else ()
    x_spec = P(*dp, *([None] * (len(lead) - len(dp))), expert_axis, None)
    v_spec = P(*dp, *([None] * (len(lead) - len(dp))), expert_axis)

    def body(params_l, x_l, valid_l=None):
        return moe_ep_body(
            params_l, x_l, cfg, expert_axis, data_axis=data_axis,
            valid_local=valid_l, diagnostics=diagnostics,
        )

    w_spec = {
        "router": P(),
        "w_in": P(expert_axis, None, None),
        "w_out": P(expert_axis, None, None),
    }
    diag_spec = {
        "expert_tokens": P(), "expert_kept": P(),
        "dropped_fraction": P(), "gate_entropy": P(),
    }
    out_specs = (x_spec, P()) + ((diag_spec,) if diagnostics else ())
    if valid is None:
        fn = shard_map(
            body, mesh=mesh, in_specs=(w_spec, x_spec),
            out_specs=out_specs,
        )
        return fn(params, x)
    fn = shard_map(
        body, mesh=mesh, in_specs=(w_spec, x_spec, v_spec),
        out_specs=out_specs,
    )
    return fn(params, x, valid)


def moe_reference(
    params: Dict[str, Any],
    x: jax.Array,
    cfg: MoEConfig,
    valid: Optional[Any] = None,
    shards: int = 1,
    return_diag: bool = False,
) -> Any:
    """Per-token oracle: route each token to its top-k experts' FFNs
    (rank-major arrival: every first choice queues before any second
    choice), gate by the raw router prob, drop assignments beyond
    capacity; invalid tokens (``valid`` mask) are skipped entirely —
    definitionally what the einsum dance computes. ``shards`` splits the
    flat token stream into P contiguous blocks with INDEPENDENT per-block
    capacity budgets — the `moe_apply_ep` distributed semantics.

    ``return_diag`` additionally returns (out, diag): the `_diag_dict`
    vocabulary computed by literal counting — routed/kept tallies per
    expert accumulated GLOBALLY across shard blocks (exactly what the
    EP flavor's psum'd diagnostics must equal), the dropped fraction,
    and the mean router-prob entropy over valid tokens."""
    import numpy as np

    xt = np.asarray(x, dtype=np.float64).reshape(-1, x.shape[-1])
    vmask = (
        np.asarray(valid).reshape(-1) if valid is not None
        else np.ones(xt.shape[0], dtype=bool)
    )
    router = np.asarray(params["router"], dtype=np.float64)
    w_in = np.asarray(params["w_in"], dtype=np.float64)
    w_out = np.asarray(params["w_out"], dtype=np.float64)
    t = xt.shape[0]
    assert t % shards == 0, (t, shards)
    t_l = t // shards
    cap = _capacity(t_l, cfg)
    logits = xt @ router
    z = np.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = z / z.sum(axis=-1, keepdims=True)
    out = np.zeros_like(xt)
    routed = np.zeros(cfg.n_experts)
    kept = np.zeros(cfg.n_experts)

    def ffn(ei, v):
        h = v @ w_in[ei]
        h = 0.5 * h * (1.0 + np.tanh(np.sqrt(2 / np.pi) * (h + 0.044715 * h**3)))
        return h @ w_out[ei]

    for b in range(shards):
        lo, hi = b * t_l, (b + 1) * t_l
        counts = {ei: 0 for ei in range(cfg.n_experts)}
        taken = [set() for _ in range(t_l)]  # experts already chosen per token
        for rank in range(cfg.top_k):
            for i in range(lo, hi):
                if not vmask[i]:
                    continue
                order = np.argsort(-probs[i])
                ei = next(int(e) for e in order if int(e) not in taken[i - lo])
                taken[i - lo].add(ei)
                routed[ei] += 1
                if counts[ei] >= cap:
                    continue
                counts[ei] += 1
                kept[ei] += 1
                out[i] += probs[i, ei] * ffn(ei, xt[i])
    out = out.reshape(x.shape)
    if not return_diag:
        return out
    n_valid = max(int(vmask.sum()), 1)
    ent = -(probs * np.log(probs + 1e-9)).sum(axis=-1)
    diag = {
        "expert_tokens": routed,
        "expert_kept": kept,
        "dropped_fraction": 1.0 - kept.sum() / max(routed.sum(), 1.0),
        "gate_entropy": float(ent[vmask].sum() / n_valid),
    }
    return out, diag
