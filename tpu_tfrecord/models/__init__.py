"""Reference consumers of the ingestion pipeline.

The reference framework ships no models (SURVEY.md §2: model-side parallelism
N/A) — its output is consumed by TensorFlow training jobs. Here two model
families are in-tree:

- ``dlrm``: a Criteo-style DLRM (the BASELINE.md north-star workload is
  Criteo-1TB ingest) whose training step exercises batch on 'data' (DP),
  embedding tables and hidden layers on 'model' (TP), and padded sequence
  features on 'seq' (SP).
- ``long_doc``: a transformer-style long-document classifier whose
  attention runs sequence-parallel over the 'seq' axis (ring or Ulysses
  all-to-all, ``LongDocConfig.sp_attention``) — the long-context consumer
  of SequenceExample ingestion (``frames``/``frames_len``).
- ``moe``: a Switch-style Mixture-of-Experts FFN with expert parallelism
  (expert-stacked weights sharded over a mesh axis, static-shape one-hot
  dispatch/combine).
- ``pipeline``: GPipe-style pipeline parallelism (stage weights sharded
  one-per-device on a 'pipe' axis, microbatches hop via ppermute;
  scale-shaped — the stream is sharded on the pipe axis and per-device
  input is O(mb)).
- ``lm``: a causal (decoder) language model — the end-to-end consumer
  proving zigzag causal ring attention, the pipelined blocks, and the
  all-to-all MoE inside one jitted, checkpointed train step
  (examples/train_lm.py).

Together the families exercise dp, tp, sp, ep, and pp on one mesh design
(all five run inside ``__graft_entry__.dryrun_multichip``).

The package-level flat names (init_params/forward/train_step/...) are the
DLRM family's, kept for compatibility; each family's full API lives on its
module (``models.dlrm``, ``models.long_doc``) — use those when working
with a specific family, the function names intentionally mirror each
other.
"""

from tpu_tfrecord.models import dlrm, lm, long_doc, moe, pipeline
from tpu_tfrecord.models.dlrm import (
    DLRMConfig,
    SparseEmbOptState,
    forward,
    init_params,
    loss_fn,
    make_synthetic_batch,
    param_shardings,
    sparse_opt_init,
    sparse_train_step,
    train_step,
)

__all__ = [
    "dlrm",
    "lm",
    "long_doc",
    "moe",
    "pipeline",
    "DLRMConfig",
    "init_params",
    "forward",
    "loss_fn",
    "train_step",
    "SparseEmbOptState",
    "sparse_opt_init",
    "sparse_train_step",
    "param_shardings",
    "make_synthetic_batch",
]
