"""Reference consumers of the ingestion pipeline.

The reference framework ships no models (SURVEY.md §2: model-side parallelism
N/A) — its output is consumed by TensorFlow training jobs. Here the flagship
consumer is in-tree: a Criteo-style DLRM (the BASELINE.md north-star workload
is Criteo-1TB ingest) whose training step exercises every mesh axis the
ingest layer produces: batch on 'data' (DP), embedding tables and hidden
layers on 'model' (TP), padded sequence features on 'seq' (SP).
"""

from tpu_tfrecord.models.dlrm import (
    DLRMConfig,
    forward,
    init_params,
    loss_fn,
    make_synthetic_batch,
    param_shardings,
    train_step,
)

__all__ = [
    "DLRMConfig",
    "init_params",
    "forward",
    "loss_fn",
    "train_step",
    "param_shardings",
    "make_synthetic_batch",
]
