"""Reference consumers of the ingestion pipeline.

The reference framework ships no models (SURVEY.md §2: model-side parallelism
N/A) — its output is consumed by TensorFlow training jobs. Here two model
families are in-tree:

- ``dlrm``: a Criteo-style DLRM (the BASELINE.md north-star workload is
  Criteo-1TB ingest) whose training step exercises batch on 'data' (DP),
  embedding tables and hidden layers on 'model' (TP), and padded sequence
  features on 'seq' (SP).
- ``long_doc``: a transformer-style long-document classifier whose
  attention runs as ring attention over the 'seq' axis — the long-context
  consumer of SequenceExample ingestion (``frames``/``frames_len``).

The package-level flat names (init_params/forward/train_step/...) are the
DLRM family's, kept for compatibility; each family's full API lives on its
module (``models.dlrm``, ``models.long_doc``) — use those when working
with a specific family, the function names intentionally mirror each
other.
"""

from tpu_tfrecord.models import dlrm, long_doc
from tpu_tfrecord.models.dlrm import (
    DLRMConfig,
    SparseEmbOptState,
    forward,
    init_params,
    loss_fn,
    make_synthetic_batch,
    param_shardings,
    sparse_opt_init,
    sparse_train_step,
    train_step,
)

__all__ = [
    "dlrm",
    "long_doc",
    "DLRMConfig",
    "init_params",
    "forward",
    "loss_fn",
    "train_step",
    "SparseEmbOptState",
    "sparse_opt_init",
    "sparse_train_step",
    "param_shardings",
    "make_synthetic_batch",
]
