"""Causal language model: the consumer that proves the model-parallel
layer end to end.

The repo's most intricate compute (zigzag causal ring attention), its
scale-shaped pipeline (models.pipeline), and its pinned all-to-all MoE
dispatch (models.moe) have oracles but — before this model — no jitted,
checkpointed train step consuming real ingested data. This decoder LM is
that consumer: packed token batches from `tpu_tfrecord.tpu.ingest.TokenPacker`
-> next-token cross-entropy, with the parallelism style picked by which
mesh axes the caller passes:

- no mesh / dp only            -> dense causal attention (the reference
                                  trajectory every other mode must match)
- ``seq_axis``                 -> ZIGZAG causal ring attention over the
                                  sequence (models.attention, balanced
                                  causal schedule, ppermute K/V rotation)
- ``pipe_axis``                -> transformer blocks stacked as pipeline
                                  stages through `pipeline_apply` — the
                                  dp×pp composed mesh; attention is dense
                                  per stage (a stage's shard_map already
                                  owns the device, so the sequence stays
                                  whole within it). ``cfg.n_virtual`` > 1
                                  interleaves V round-robin chunks per
                                  device (models.pipeline), cutting the
                                  bubble toward (S-1)/(V·M+S-1)
- ``fsdp_axis``                -> GSPMD weight sharding (FSDP): every 2D+
                                  parameter shards one dimension over the
                                  axis at rest (`SpecLayout` is the spec
                                  table), an all-gather materializes each
                                  weight ON USE inside `_block`/`forward`,
                                  and `train_step` constrains grads back
                                  to the sharded layout so gradients and
                                  optimizer state NEVER gather — per-
                                  device param+opt bytes shrink ~linearly
                                  in the axis (pinned). Composes with dp,
                                  pp (the pipeline's param_spec boundary
                                  does the per-step gather of each stage's
                                  own weights), and EP (expert weights
                                  shard expert×fsdp; the MoE shard_map
                                  gathers only the fsdp dim — activations
                                  are never re-sharded through the host)
- `LMStream`                   -> the SERVING flavor: the same pipelined
                                  chunks behind a per-microbatch streamed
                                  step (push one [mb, L+1] request, pop
                                  logits), bitwise the batch path
- ``expert_axis``              -> every block's FFN swaps for the top-k
                                  MoE with the PINNED all-to-all dispatch
                                  (`moe_apply_ep`)

All modes share one parameter pytree (blocks stacked on a leading
[n_layers, ...] dim — exactly the pipeline's stage layout), so the same
checkpoint trains under any mesh and the composition tests can demand
same-params same-data same-loss-trajectory across modes.

TPU shaping follows models.long_doc: pre-norm residual blocks, batched
matmuls, one jit per train step, no data-dependent control flow.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_tfrecord.models import moe as _moe
from tpu_tfrecord.models import pipeline as _pipeline
from tpu_tfrecord.models.attention import attention_reference, ring_attention
from tpu_tfrecord.models.long_doc import _rms_norm


@dataclass(frozen=True)
class LMConfig:
    vocab_size: int = 256
    d_model: int = 32
    n_heads: int = 4
    n_layers: int = 2
    mlp_mult: int = 4
    max_len: int = 64        # L: the model reads L tokens, predicts L
    dtype: Any = jnp.float32
    # 'seq'-axis attention flavor: zigzag (balanced causal ring) is the
    # default — the schedule this model exists to prove; False falls back
    # to the contiguous causal ring
    zigzag: bool = True
    # > 0 swaps every block's dense FFN for the top-k MoE (models.moe);
    # with an ``expert_axis`` the dispatch is the pinned all-to-all EP
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # microbatches for the pipeline mode (must divide the batch); None =
    # 2 × pipe-axis size (a 2-slice block per device, 2/3 efficiency)
    n_micro: Optional[int] = None
    # interleaved virtual stages for the pipeline mode (GSPMD-style,
    # models.pipeline): device d owns V round-robin layer chunks
    # (d, d+S, ...), shrinking the bubble toward (S-1)/(V·M+S-1);
    # n_layers must divide by S·V
    n_virtual: int = 1


@dataclass(frozen=True)
class SpecLayout:
    """The LM's mesh-axis spec table: one place that says which axis each
    parameter dimension shards over (the SNIPPETS [3] `SpecLayout` idiom).
    Any axis may be None — the spec degrades to replication on that
    dimension — so ONE table serves every mesh composition: pure dp (all
    None), dp×fsdp, dp×pp, dp×fsdp×pp, and dp×fsdp×EP.

    Conventions: the stacked block dim ([n_layers, ...]) belongs to
    ``pipe_axis`` (stage slicing); the first WEIGHT dim after it (fan-in
    for dense kernels, d_model for the router, rows for embed/pos/head)
    belongs to ``fsdp_axis``; the expert dim of MoE kernels belongs to
    ``expert_axis``. 1-D-per-layer biases replicate over fsdp — sharding
    them buys nothing and costs a gather each.
    """

    fsdp_axis: Optional[str] = None
    pipe_axis: Optional[str] = None
    expert_axis: Optional[str] = None

    def embed(self) -> P:                       # [vocab, d_model]
        return P(self.fsdp_axis, None)

    def pos(self) -> P:                         # [max_len, d_model]
        return P(self.fsdp_axis, None)

    def head(self) -> Dict[str, P]:             # w [d_model, vocab]
        return {"w": P(self.fsdp_axis, None), "b": P()}

    def block_dense(self) -> Dict[str, P]:      # w [n_layers, fan_in, fan_out]
        return {
            "w": P(self.pipe_axis, self.fsdp_axis, None),
            "b": P(self.pipe_axis, None),
        }

    def moe(self) -> Dict[str, P]:              # w_in [n_layers, E, d_model, d_ff]
        return {
            "router": P(self.pipe_axis, self.fsdp_axis, None),
            "w_in": P(self.pipe_axis, self.expert_axis, self.fsdp_axis, None),
            "w_out": P(self.pipe_axis, self.expert_axis, self.fsdp_axis, None),
        }


def param_specs(params, layout: SpecLayout) -> Dict[str, Any]:
    """PartitionSpec pytree matching ``params``' structure, leaf-for-leaf,
    from the spec table. Used by `param_shardings` for placement and by
    `train_step` to constrain grads back to the sharded layout."""
    blocks: Dict[str, Any] = {}
    for name in params["blocks"]:
        blocks[name] = layout.moe() if name == "moe" else layout.block_dense()
    return {
        "embed": layout.embed(),
        "pos": layout.pos(),
        "head": layout.head(),
        "blocks": blocks,
    }


def _unshard_fn(mesh, fsdp_axis):
    """The FSDP gather-on-use: a pytree-wide ``with_sharding_constraint``
    to full replication, forcing XLA to all-gather the weight right where
    it is consumed (and, in the transpose, to keep the weight's cotangent
    from staying replicated — the grad constraint in `train_step` turns
    that into a reduce+slice, never a gather of grads). Identity when no
    fsdp axis is in play, so every other mode compiles the exact
    pre-fsdp program."""
    if mesh is None or fsdp_axis is None:
        return lambda t: t
    repl = NamedSharding(mesh, P())
    return lambda t: jax.tree.map(
        lambda a: jax.lax.with_sharding_constraint(a, repl), t
    )


def _dense_init(rng, fan_in: int, fan_out: int):
    kw, kb = jax.random.split(rng)
    scale = (1.0 / fan_in) ** 0.5
    return {
        "w": jax.random.normal(kw, (fan_in, fan_out), jnp.float32) * scale,
        "b": jax.random.normal(kb, (fan_out,), jnp.float32) * 0.0,
    }


def _dense(layer, x, dt):
    return x @ layer["w"].astype(dt) + layer["b"].astype(dt)


def init_params(rng: jax.Array, cfg: LMConfig) -> Dict[str, Any]:
    if cfg.d_model % cfg.n_heads:
        raise ValueError(
            f"n_heads ({cfg.n_heads}) must divide d_model ({cfg.d_model})"
        )
    keys = jax.random.split(rng, 3 + cfg.n_layers)
    params: Dict[str, Any] = {
        "embed": jax.random.normal(
            keys[0], (cfg.vocab_size, cfg.d_model), jnp.float32
        )
        * 0.02,
        "pos": jax.random.normal(
            keys[1], (cfg.max_len, cfg.d_model), jnp.float32
        )
        * 0.02,
        "head": _dense_init(keys[2], cfg.d_model, cfg.vocab_size),
    }
    layers = []
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[3 + i], 4)
        layer = {
            "qkv": _dense_init(k[0], cfg.d_model, 3 * cfg.d_model),
            "proj": _dense_init(k[1], cfg.d_model, cfg.d_model),
        }
        if cfg.moe_experts > 0:
            layer["moe"] = _moe.init_params(k[2], _moe_cfg(cfg))
        else:
            layer["mlp_in"] = _dense_init(
                k[2], cfg.d_model, cfg.mlp_mult * cfg.d_model
            )
            layer["mlp_out"] = _dense_init(
                k[3], cfg.mlp_mult * cfg.d_model, cfg.d_model
            )
        layers.append(layer)
    # blocks STACKED on a leading [n_layers, ...] dim: the dense loop
    # slices it, the pipeline shards it — one checkpoint, every mesh
    params["blocks"] = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    return params


def _moe_cfg(cfg: LMConfig) -> "_moe.MoEConfig":
    return _moe.MoEConfig(
        d_model=cfg.d_model,
        d_ff=cfg.mlp_mult * cfg.d_model,
        n_experts=cfg.moe_experts,
        capacity_factor=cfg.moe_capacity_factor,
        top_k=cfg.moe_top_k,
        dtype=cfg.dtype,
    )


def _block(
    layer, x, cfg: LMConfig, mesh=None, seq_axis=None, data_axis=None,
    expert_axis=None, fsdp_axis=None, segments=None, diagnostics=False,
):
    """One pre-norm decoder block on x [B, L, D]. Attention flavor: zigzag
    causal ring over ``seq_axis`` when given, else dense causal;
    ``segments`` [B, L] masks attention across packed-document boundaries
    in either flavor. With ``fsdp_axis``, every weight is gathered ON USE
    (`_unshard_fn`) — EXCEPT the EP path's expert weights, whose reshard
    belongs to the MoE shard_map boundary (it gathers the fsdp dim while
    KEEPING the expert dim sharded; a full gather here would undo EP).
    Returns (x, aux, moe_diag) — moe_diag is None unless ``diagnostics``
    is set on an MoE block (models.moe _diag_dict vocabulary)."""
    dt = cfg.dtype
    g = _unshard_fn(mesh, fsdp_axis)
    b, l, _ = x.shape
    h, dh = cfg.n_heads, cfg.d_model // cfg.n_heads
    qkv = _dense(g(layer["qkv"]), _rms_norm(x), dt)
    q, k, v = (a.reshape(b, l, h, dh) for a in jnp.split(qkv, 3, axis=-1))
    if mesh is not None and seq_axis is not None:
        att = ring_attention(
            q, k, v, mesh, seq_axis=seq_axis, data_axis=data_axis,
            causal=True, zigzag=cfg.zigzag, segments=segments,
        )
    else:
        att = attention_reference(q, k, v, causal=True, segments=segments)
    x = x + _dense(g(layer["proj"]), att.reshape(b, l, cfg.d_model), dt)
    if cfg.moe_experts > 0:
        if mesh is not None and expert_axis is not None:
            out = _moe.moe_apply_ep(
                layer["moe"], _rms_norm(x), _moe_cfg(cfg), mesh,
                expert_axis=expert_axis, data_axis=data_axis,
                diagnostics=diagnostics,
            )
        else:
            out = _moe.moe_apply(
                g(layer["moe"]), _rms_norm(x), _moe_cfg(cfg),
                diagnostics=diagnostics,
            )
        y, aux = out[0], out[1]
        return x + y, aux, (out[2] if diagnostics else None)
    y = _dense(g(layer["mlp_in"]), _rms_norm(x), dt)
    return (
        x + _dense(g(layer["mlp_out"]), jax.nn.gelu(y), dt),
        jnp.float32(0.0),
        None,
    )


def _embed_tokens(params, tokens, cfg: LMConfig, segments=None):
    """tokens [B, L+1] int32 -> x [B, L, D]: the model reads
    tokens[:, :-1]. Shared by the batch forward and the streamed server
    (LMStream) — one embedding program, no drift between paths.

    ``segments`` [B, L+1] (TokenPacker bin modes) switches the position
    embedding to PER-DOCUMENT positions derived in-jit from the ids: each
    segment restarts at position 0, so a document packed mid-row embeds
    exactly as it would alone at the row start — half of the per-document
    oracle (the attention segment mask is the other half). The data
    contract stays segment_ids-only; no position column is ever fed."""
    dt = cfg.dtype
    x_tok = tokens[:, :-1]
    l = x_tok.shape[1]
    if l != cfg.max_len:
        raise ValueError(
            f"packed batch carries {l} input tokens but cfg.max_len is "
            f"{cfg.max_len} (the packer's seq_len must match)"
        )
    if segments is None:
        pos = params["pos"][:l].astype(dt)[None]
    else:
        segs = segments[:, :-1]
        idx = jnp.arange(l, dtype=jnp.int32)
        # a segment starts where the id changes (position 0 always does);
        # running cummax of the start indices = each position's segment
        # start, so idx - start is the within-document position
        boundary = jnp.concatenate(
            [
                jnp.ones((segs.shape[0], 1), bool),
                segs[:, 1:] != segs[:, :-1],
            ],
            axis=1,
        )
        start = jax.lax.cummax(
            jnp.where(boundary, idx[None, :], 0), axis=1
        )
        pos = params["pos"].astype(dt)[idx[None, :] - start]   # [B, L, D]
    return params["embed"].astype(dt)[x_tok] + pos


def _head_logits(params, x, cfg: LMConfig):
    """Final-norm + LM head: [.., L, D] -> f32 logits [.., L, V]. Shared
    by the batch forward and LMStream."""
    return _dense(params["head"], _rms_norm(x), cfg.dtype).astype(
        jnp.float32
    )


def _chunk_count(cfg: LMConfig, n_stages: int) -> int:
    chunks = n_stages * cfg.n_virtual
    if cfg.n_layers % chunks:
        raise ValueError(
            f"n_layers ({cfg.n_layers}) must divide into the pipe axis × "
            f"n_virtual ({n_stages} stages × {cfg.n_virtual} virtual = "
            f"{chunks} chunks)"
        )
    return chunks


def _stage_stack(blocks, cfg: LMConfig, n_stages: int):
    """The stacked [n_layers, ...] block pytree in the pipeline's stage
    layout: [S, per_stage, ...] classic, or [S, V, per_chunk, ...]
    interleaved — virtual stage k = v·S + s (device s's chunk v) holds
    layers [k·pc, (k+1)·pc), the GSPMD round-robin assignment (device d
    owns layer chunks d, d+S, d+2S, …). The V>1 relayout is a strided
    transpose: place/checkpoint params in the canonical [n_layers, ...]
    stack and let XLA move them once per step, or pre-place the reshaped
    stack (LMStream does, serving from the same checkpoint)."""
    chunks = _chunk_count(cfg, n_stages)
    pc = cfg.n_layers // chunks
    if cfg.n_virtual == 1:
        return jax.tree.map(
            lambda a: a.reshape((n_stages, pc) + a.shape[1:]), blocks
        )
    v = cfg.n_virtual
    return jax.tree.map(
        lambda a: a.reshape((v, n_stages, pc) + a.shape[1:]).transpose(
            (1, 0) + tuple(range(2, a.ndim + 2))
        ),
        blocks,
    )


def _make_stage_fn(cfg: LMConfig):
    """One pipeline chunk: per_chunk decoder blocks, dense attention (a
    stage's shard_map already owns the device — the sequence stays whole
    within it)."""
    def stage_fn(p_chunk, xs):
        pc = jax.tree.leaves(p_chunk)[0].shape[0]
        for j in range(pc):
            layer = jax.tree.map(lambda a: a[j], p_chunk)
            xs, _, _ = _block(layer, xs, cfg)
        return xs

    return stage_fn


def forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: LMConfig,
    mesh: Optional[Mesh] = None,
    data_axis: Optional[str] = None,
    seq_axis: Optional[str] = None,
    pipe_axis: Optional[str] = None,
    expert_axis: Optional[str] = None,
    fsdp_axis: Optional[str] = None,
    segments: Optional[jax.Array] = None,
    diagnostics: bool = False,
):
    """tokens [B, L+1] int32 -> (logits [B, L, V] f32, aux f32[, diag]).
    The model reads tokens[:, :-1]; the caller scores against
    tokens[:, 1:] (`loss_fn` does). Mesh axes select the parallelism
    (module docstring); pipe and seq modes are mutually exclusive (a
    pipeline stage owns its devices — the sequence stays whole within
    it).

    ``fsdp_axis`` adds GSPMD weight sharding to ANY of the other modes:
    embed/pos/head gather on use here, each dense-loop block gathers its
    own layer inside `_block` (peak unsharded weight residency = one
    layer), and the pipeline mode needs no change at all — its
    `pipeline_apply` param_spec (P(pipe)) boundary reshards each stage's
    weights from the at-rest P(pipe, fsdp, ...) placement, which IS the
    per-step gather-on-use, composed with stage slicing.

    ``segments`` [B, L+1] int32 (TokenPacker bin modes) masks attention
    across packed-document boundaries and switches to per-document
    positions (`_embed_tokens`); not supported in the pipeline mode —
    its stage stream carries activations only.

    ``diagnostics`` (a static flag — False compiles the exact pre-flag
    program) returns a third element: the in-jit model diagnostics dict
    (ISSUE 13). MoE models carry ``expert_tokens``/``expert_kept`` [E]
    (summed across layers), ``dropped_fraction``, ``gate_entropy``
    (averaged across layers); the pipeline mode carries the measured
    ``bubble_fraction``/``useful_ticks``/``total_ticks``. All
    static-shaped and stop_gradient'd by the underlying layers."""
    if pipe_axis is not None and seq_axis is not None:
        raise ValueError(
            "pipe_axis and seq_axis are mutually exclusive: inside a "
            "pipeline stage the sequence is not sharded"
        )
    if pipe_axis is not None and cfg.moe_experts > 0:
        raise ValueError(
            "moe_experts > 0 is not supported in the pipeline mode"
        )
    if pipe_axis is not None and segments is not None:
        raise ValueError(
            "segments are not supported in the pipeline mode: the stage "
            "stream carries activations only (pack with the default "
            "slice mode, or drop pipe_axis)"
        )
    b = tokens.shape[0]
    if fsdp_axis is not None:
        # gather-on-use for the non-stacked params; the blocks gather
        # per-layer in `_block` (dense loop) or at the pipeline_apply
        # boundary (pipe mode)
        g = _unshard_fn(mesh, fsdp_axis)
        params = dict(params)
        params["embed"] = g(params["embed"])
        params["pos"] = g(params["pos"])
        params["head"] = g(params["head"])
    # _embed_tokens owns the max_len validation
    x = _embed_tokens(params, tokens, cfg, segments=segments)  # [B, L, D]
    segs_in = segments[:, :-1] if segments is not None else None
    aux_total = jnp.float32(0.0)
    diag: Dict[str, jax.Array] = {}
    if pipe_axis is not None:
        n_stages = mesh.shape[pipe_axis]
        stage_params = _stage_stack(params["blocks"], cfg, n_stages)
        m = cfg.n_micro or 2 * n_stages
        if b % m:
            raise ValueError(f"batch {b} not divisible by n_micro {m}")
        stage_fn = _make_stage_fn(cfg)
        xs = x.reshape((m, b // m) + x.shape[1:])              # [M, mb, L, D]
        batch_spec = P(data_axis) if data_axis else P()
        out = _pipeline.pipeline_apply(
            stage_fn, stage_params, xs, mesh, pipe_axis=pipe_axis,
            batch_spec=batch_spec, n_virtual=cfg.n_virtual,
            diagnostics=diagnostics,
        )
        if diagnostics:
            xs, diag = out
        else:
            xs = out
        x = xs.reshape((b,) + xs.shape[2:])
    else:
        moe_diags = []
        for i in range(cfg.n_layers):
            layer = jax.tree.map(lambda a: a[i], params["blocks"])
            x, aux, mdiag = _block(
                layer, x, cfg, mesh=mesh, seq_axis=seq_axis,
                data_axis=data_axis, expert_axis=expert_axis,
                fsdp_axis=fsdp_axis, segments=segs_in,
                diagnostics=diagnostics,
            )
            aux_total = aux_total + aux
            if mdiag is not None:
                moe_diags.append(mdiag)
        if moe_diags:
            n = len(moe_diags)
            # counts SUM across layers (every layer routes the full
            # stream: expert_tokens sums to n_layers * T * top_k);
            # fractions/entropy AVERAGE — the per-layer regime
            diag = {
                "expert_tokens": sum(d["expert_tokens"] for d in moe_diags),
                "expert_kept": sum(d["expert_kept"] for d in moe_diags),
                "dropped_fraction":
                    sum(d["dropped_fraction"] for d in moe_diags) / n,
                "gate_entropy":
                    sum(d["gate_entropy"] for d in moe_diags) / n,
            }
    logits = _head_logits(params, x, cfg)
    if diagnostics:
        return logits, aux_total, diag
    return logits, aux_total


def loss_fn(params, tokens, cfg: LMConfig, mesh=None, data_axis=None,
            seq_axis=None, pipe_axis=None, expert_axis=None,
            fsdp_axis=None, segments=None, diagnostics: bool = False):
    """Mean next-token cross-entropy + the MoE aux loss. Without
    ``segments`` every position scores (slice packing leaves no padding);
    with them (bin packing) a position is valid only when the input token
    and its target share a nonzero segment — no document's last token is
    ever scored against the NEXT document's first, and pad positions
    (segment 0) never contribute. With ``diagnostics`` returns
    (loss, diag) — the has_aux shape value_and_grad wants."""
    out = forward(
        params, tokens, cfg, mesh, data_axis, seq_axis, pipe_axis,
        expert_axis, fsdp_axis=fsdp_axis, segments=segments,
        diagnostics=diagnostics,
    )
    logits, aux = out[0], out[1]
    targets = tokens[:, 1:].astype(jnp.int32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    tok_ce = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if segments is None:
        ce = jnp.mean(tok_ce)
    else:
        valid = (segments[:, :-1] == segments[:, 1:]) & (segments[:, 1:] != 0)
        ce = jnp.sum(tok_ce * valid) / jnp.maximum(valid.sum(), 1)
    loss = ce + cfg.moe_aux_weight * aux
    if diagnostics:
        return loss, out[2]
    return loss


def train_step(params, opt_state, tokens, cfg: LMConfig, tx, mesh=None,
               data_axis=None, seq_axis=None, pipe_axis=None,
               expert_axis=None, fsdp_axis=None, segments=None,
               diagnostics: bool = False):
    """One optimizer step; jit this whole function (mesh static via
    closure/partial). Returns (params, opt_state, loss) — with
    ``diagnostics``, (params, opt_state, loss, diag): the in-jit model
    diagnostics ride the step's outputs, so reading them costs no extra
    compilation or device round trip beyond fetching the tiny dict.

    With ``fsdp_axis`` the grads are constrained back to the parameter
    layout (`param_specs`) right out of the backward pass: the optimizer
    update and its state run SHARDED — cross-replica grad reduction goes
    through a reduce+slice on the sharded layout, and no full all-gather
    of grads ever exists in the step."""
    if diagnostics:
        (loss, diag), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, tokens, cfg, mesh, data_axis, seq_axis, pipe_axis,
            expert_axis, fsdp_axis, segments, diagnostics=True,
        )
    else:
        loss, grads = jax.value_and_grad(loss_fn)(
            params, tokens, cfg, mesh, data_axis, seq_axis, pipe_axis,
            expert_axis, fsdp_axis, segments,
        )
    if mesh is not None and fsdp_axis is not None:
        layout = SpecLayout(
            fsdp_axis=fsdp_axis, pipe_axis=pipe_axis,
            expert_axis=expert_axis,
        )
        grads = jax.tree.map(
            lambda g, s: jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, s)
            ),
            grads,
            param_specs(grads, layout),
        )
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree.map(lambda p, u: p + u, params, updates)
    if diagnostics:
        return params, opt_state, loss, diag
    return params, opt_state, loss


def param_shardings(
    mesh: Mesh,
    params,
    pipe_axis: Optional[str] = None,
    expert_axis: Optional[str] = None,
    fsdp_axis: Optional[str] = None,
):
    """NamedShardings for the parameter pytree from the `SpecLayout` spec
    table: the stacked block dim shards on ``pipe_axis`` (stage weights
    never replicate — that is PP), the expert dim on ``expert_axis``
    (EP), and every 2D+ weight's leading weight dim on ``fsdp_axis``
    (FSDP at rest; the forward gathers on use). Axes left None degrade
    to replication on that dim, so this is exactly the old behavior for
    the old calls.

    The checkpoint keeps the canonical [n_layers, ...] stack under every
    mode; with ``cfg.n_virtual`` > 1 the forward's `_stage_stack` does
    the round-robin chunk relayout in-jit (XLA moves the weights once per
    step) — serving avoids even that by pre-placing the reshaped stack
    (LMStream)."""
    layout = SpecLayout(
        fsdp_axis=fsdp_axis, pipe_axis=pipe_axis, expert_axis=expert_axis
    )
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_specs(params, layout)
    )


def batch_shardings(mesh: Mesh, data_axis: str = "data"):
    """Packed token batches shard their batch dim on the data axis."""
    return {"tokens": NamedSharding(mesh, P(data_axis, None))}


class LMStream:
    """Microbatch-streamed LM inference — the serving flavor of the
    pipeline mode (ROADMAP #2's heavy-traffic path).

    Wraps `models.pipeline.PipelineStream` around the SAME decoder chunks
    the pipelined trainer runs: blocks from the trainer's checkpoint
    layout ([n_layers, ...] stacked — `examples/train_lm.py`'s npz loads
    straight in) are re-stacked into the stage layout host-side and
    device_put sharded on the pipe axis, so serving pays the V>1
    round-robin relayout ONCE at startup instead of per step. Embedding
    and head run per-microbatch in their own tiny jits (the exact
    programs the batch forward uses).

    Per request: ``submit(tokens [mb, L+1])`` feeds ONE microbatch-sized
    slice (the per-call pin — no request stream is ever materialized) and
    returns whatever logits completed, FIFO; ``flush()`` drains the tail.
    Streamed logits are BITWISE equal to `batch_reference` — the batch
    path over `pipeline_apply` on the same slices (pinned by tests), so
    the serving surface cannot drift from the trained graph.
    """

    def __init__(
        self,
        params: Dict[str, Any],
        cfg: LMConfig,
        mesh: Mesh,
        pipe_axis: str = "pipe",
    ):
        self.cfg = cfg
        self._n_stages = mesh.shape[pipe_axis]
        _chunk_count(cfg, self._n_stages)
        if cfg.moe_experts > 0:
            raise ValueError(
                "moe_experts > 0 is not supported in the pipeline mode"
            )
        self._stage_fn = _make_stage_fn(cfg)
        self._stage_params = jax.device_put(
            _stage_stack(params["blocks"], cfg, self._n_stages),
            NamedSharding(mesh, P(pipe_axis)),
        )
        self._ep = {"embed": params["embed"], "pos": params["pos"]}
        self._hp = {"head": params["head"]}
        self._embed = jax.jit(lambda p, t: _embed_tokens(p, t, cfg))
        self._head = jax.jit(lambda p, x: _head_logits(p, x, cfg))
        self._mesh = mesh
        self._pipe_axis = pipe_axis
        self.stream = _pipeline.PipelineStream(
            self._stage_fn, self._stage_params, mesh, pipe_axis=pipe_axis,
            n_virtual=cfg.n_virtual,
        )

    def submit(self, tokens) -> list:
        """One request: tokens [mb, L+1] int32 in, zero or more finished
        [mb, L, V] f32 logits out (FIFO — outputs lag by the pipeline's
        S·V-tick latency)."""
        return [out for out, _ in self.submit_tagged(tokens)]

    def submit_tagged(self, tokens, tag=None) -> list:
        """`submit` riding an opaque host-side tag on the microbatch (see
        `PipelineStream.push_tagged`); returns ``(logits, tag)`` pairs so
        a multiplexer can map each popped [mb, L, V] back to the requests
        packed into its slots. The tag stays on the host — the compiled
        step and its argument bytes are untouched."""
        x = self._embed(self._ep, jnp.asarray(tokens))
        return [
            (np.asarray(self._head(self._hp, o)), t)
            for o, t in self.stream.push_tagged(x, tag)
        ]

    def flush(self) -> list:
        """Drain the in-flight tail; returns the remaining logits FIFO."""
        return [out for out, _ in self.flush_tagged()]

    def flush_tagged(self) -> list:
        """`flush` returning ``(logits, tag)`` pairs (see `submit_tagged`)."""
        return [
            (np.asarray(self._head(self._hp, o)), t)
            for o, t in self.stream.flush_tagged()
        ]

    def reset(self) -> None:
        self.stream.reset()

    def batch_reference(self, batches) -> list:
        """The batch path on the same slices: the SAME embed/head jits
        around batch-mode `pipeline_apply` over the stacked [M, mb, ...]
        stream — what the streamed outputs must equal bitwise."""
        xs = jnp.stack(
            [self._embed(self._ep, jnp.asarray(t)) for t in batches]
        )
        out = _pipeline.pipeline_apply(
            self._stage_fn, self._stage_params, xs, self._mesh,
            pipe_axis=self._pipe_axis, n_virtual=self.cfg.n_virtual,
        )
        return [
            np.asarray(self._head(self._hp, out[i]))
            for i in range(len(batches))
        ]


def pack_slots(windows, mb: int, max_len: int) -> np.ndarray:
    """Pack up to ``mb`` per-request token windows ([L] int32 each) into
    one [mb, L+1] microbatch for `LMStream.submit`: row i holds request
    i's window plus a zero trailing token (column L is the training
    target slot — `_embed_tokens` drops it, so its value never reaches
    the forward), and unused slots are all-zero. Slot VALIDITY lives
    host-side (the submit tag), not in the array: every model op is
    batch-row independent, so a garbage slot cannot perturb a valid one
    bitwise (the per-slot isolation pin continuous batching rests on)."""
    if len(windows) > mb:
        raise ValueError(f"{len(windows)} windows > {mb} slots")
    out = np.zeros((mb, max_len + 1), np.int32)
    for i, w in enumerate(windows):
        w = np.asarray(w, dtype=np.int32)
        if w.shape != (max_len,):
            raise ValueError(f"window {i} shape {w.shape} != ({max_len},)")
        out[i, :max_len] = w
    return out


def make_synthetic_tokens(
    cfg: LMConfig, batch_size: int, seed: int = 0, n_next: int = 4
) -> np.ndarray:
    """[B, L+1] int32 batches from a fixed sparse-bigram language: each
    token has ``n_next`` plausible successors, so next-token CE can fall
    from ~ln(V) toward ~ln(n_next) — training signal without real text."""
    rng = np.random.default_rng(seed)
    table = bigram_table(cfg.vocab_size, n_next, seed=1234)
    out = np.empty((batch_size, cfg.max_len + 1), np.int32)
    for i in range(batch_size):
        t = int(rng.integers(cfg.vocab_size))
        for j in range(cfg.max_len + 1):
            out[i, j] = t
            t = int(table[t, rng.integers(n_next)])
    return out


def bigram_table(vocab: int, n_next: int, seed: int = 1234) -> np.ndarray:
    """[V, n_next] successor table — the synthetic 'language' shared by
    tests, the example generator, and the bench probe."""
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, size=(vocab, n_next)).astype(np.int32)
