"""Criteo-style DLRM: the flagship consumer of the ingest pipeline.

Pure-JAX functional model (params are a plain pytree) designed TPU-first:

- all compute is batched matmuls/gathers that tile onto the MXU; bfloat16
  activations with float32 params/accumulation;
- embedding tables are sharded over the 'model' mesh axis (row/vocab dim) —
  gathers on a sharded table make XLA insert the all-to-all/allgather
  collectives (tensor parallelism over ICI);
- an optional sequence tower consumes padded SequenceExample frames
  [B, L, D] with L shardable over a 'seq' axis (sequence/context
  parallelism for the long-context path);
- the train step is a single jit: loss -> grad -> optax update, donated
  params, no data-dependent Python control flow.

Batch layout matches tpu_tfrecord.tpu.ingest.host_batch_from_columnar output
for a Criteo-like schema: 'dense' [B, 13] f32, 'cat' [B, 26] i64 (hashed),
'label' [B] f32, optionally 'frames' [B, L, D] + 'frames_len' [B].
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class DLRMConfig:
    num_dense: int = 13
    num_categorical: int = 26
    vocab_size: int = 1024          # per-feature hash buckets
    embed_dim: int = 32
    bottom_mlp: Tuple[int, ...] = (64, 32)
    top_mlp: Tuple[int, ...] = (64, 1)
    seq_len: int = 0                # 0 = no sequence tower
    seq_dim: int = 0
    dtype: Any = jnp.bfloat16       # activation dtype (MXU-friendly)
    # 'cat': concatenate bottom output + flattened embeddings (simple);
    # 'dot': classic DLRM pairwise dot interaction over [bottom_out; embs]
    #        (Pallas kernel on TPU; requires bottom_mlp[-1] == embed_dim)
    interaction: str = "cat"


def _dense_init(rng, fan_in: int, fan_out: int, gain: float = 2.0):
    """He-style dense init ({'w','b'} dict); shared by the model families
    (gain=2 for relu stacks, 1 for pre-norm residual blocks)."""
    scale = np.sqrt(gain / fan_in)
    w = jax.random.normal(rng, (fan_in, fan_out), jnp.float32) * scale
    return {"w": w, "b": jnp.zeros((fan_out,), jnp.float32)}


def init_params(rng: jax.Array, cfg: DLRMConfig) -> Dict[str, Any]:
    keys = jax.random.split(rng, 8)
    params: Dict[str, Any] = {
        # one stacked table [F, V, D]: a single large gather beats F small
        # ones (fewer kernels, better HBM streaming)
        "embeddings": jax.random.normal(
            keys[0], (cfg.num_categorical, cfg.vocab_size, cfg.embed_dim), jnp.float32
        )
        * 0.05,
    }
    bottom = []
    fan = cfg.num_dense
    for i, width in enumerate(cfg.bottom_mlp):
        bottom.append(_dense_init(jax.random.fold_in(keys[1], i), fan, width))
        fan = width
    params["bottom"] = bottom
    if cfg.interaction == "dot":
        if cfg.bottom_mlp[-1] != cfg.embed_dim:
            raise ValueError(
                "interaction='dot' requires bottom_mlp[-1] == embed_dim "
                f"(got {cfg.bottom_mlp[-1]} vs {cfg.embed_dim})"
            )
        n_feat = cfg.num_categorical + 1  # embeddings + bottom output
        interact_dim = cfg.bottom_mlp[-1] + n_feat * (n_feat - 1) // 2
    elif cfg.interaction == "cat":
        interact_dim = cfg.bottom_mlp[-1] + cfg.num_categorical * cfg.embed_dim
    else:
        raise ValueError(f"unknown interaction {cfg.interaction!r}")
    if cfg.seq_len:
        interact_dim += cfg.embed_dim
        params["seq_proj"] = _dense_init(keys[3], cfg.seq_dim, cfg.embed_dim)
    top = []
    fan = interact_dim
    for i, width in enumerate(cfg.top_mlp):
        top.append(_dense_init(jax.random.fold_in(keys[2], i), fan, width))
        fan = width
    params["top"] = top
    return params


def _mlp(layers, x, dtype):
    for i, layer in enumerate(layers):
        x = x @ layer["w"].astype(dtype) + layer["b"].astype(dtype)
        if i + 1 < len(layers):
            x = jax.nn.relu(x)
    return x


def forward(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: DLRMConfig,
    emb: Optional[jax.Array] = None,
) -> jax.Array:
    """Logits [B]. bfloat16 activations, float32 output.

    ``emb`` optionally supplies the gathered embedding rows [B, F, D]
    directly (the sparse-update path differentiates w.r.t. the rows, not
    the table — see ``sparse_train_step``); ``params['embeddings']`` is not
    touched when it is given."""
    dt = cfg.dtype
    dense = batch["dense"].astype(dt)
    bottom_out = _mlp(params["bottom"], dense, dt)          # [B, H]
    if emb is None:
        # [B, F] indices into [F, V, D] -> [B, F, D]
        emb = jnp.take_along_axis(
            params["embeddings"].astype(dt)[None],          # [1, F, V, D]
            batch["cat"][:, :, None, None],                  # [B, F, 1, 1]
            axis=2,
        )[:, :, 0, :]
    else:
        emb = emb.astype(dt)
    if cfg.interaction == "dot":
        from tpu_tfrecord.models.interaction import dot_interaction

        stack = jnp.concatenate([bottom_out[:, None, :], emb], axis=1)
        pairs = dot_interaction(stack)                       # [B, P]
        feats = [bottom_out, pairs.astype(dt)]
    else:
        feats = [bottom_out, emb.reshape(emb.shape[0], -1)]
    if cfg.seq_len:
        frames = batch["frames"].astype(dt)                  # [B, L, D_in]
        proj = _mlp([params["seq_proj"]], frames, dt)        # [B, L, D]
        mask = (
            jnp.arange(frames.shape[1])[None, :] < batch["frames_len"][:, None]
        ).astype(dt)
        pooled = (proj * mask[:, :, None]).sum(axis=1) / jnp.maximum(
            mask.sum(axis=1, keepdims=True), 1.0
        )
        feats.append(pooled)
    x = jnp.concatenate(feats, axis=-1)
    logits = _mlp(params["top"], x, dt)
    return logits[:, 0].astype(jnp.float32)


def loss_fn(params, batch, cfg: DLRMConfig, emb: Optional[jax.Array] = None) -> jax.Array:
    logits = forward(params, batch, cfg, emb=emb)
    labels = batch["label"].astype(jnp.float32)
    # numerically-stable BCE-with-logits
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def train_step(params, opt_state, batch, cfg: DLRMConfig, tx):
    """One SGD step: loss -> grad -> optax update. Jit this whole function.

    The embedding gradient here is DENSE ([F, V, D], same shape as the
    table): simple and exact, but at real Criteo vocabularies (2^20+ rows)
    each step would materialize a multi-GB zero-mostly tensor. Use
    ``sparse_train_step`` for large tables."""
    loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree.map(lambda p, u: p + u, params, updates)
    return params, opt_state, loss


class SparseEmbOptState(NamedTuple):
    """Optimizer state for ``sparse_train_step``: the wrapped optax state
    for the non-embedding params plus the row-wise AdaGrad accumulators
    ([F, V] float32 — D-independent, so 2^20-row tables carry ~4MB of
    state per feature column instead of an optimizer-state copy of the
    table)."""

    dense: Any
    accum: jax.Array


def sparse_opt_init(params, cfg: DLRMConfig, tx) -> SparseEmbOptState:
    dense = {k: v for k, v in params.items() if k != "embeddings"}
    return SparseEmbOptState(
        dense=tx.init(dense),
        accum=jnp.zeros((cfg.num_categorical, cfg.vocab_size), jnp.float32),
    )


# Largest F*V for which a flat int32 (f*V + v) dedup key cannot wrap (the
# default JAX index dtype with x64 disabled). Module-level so tests can
# shrink it and pin both sort paths against each other at test scale.
_FLAT_KEY_MAX = 2**31 - 1


def _dedup_sort(f_flat, v_flat, vocab: int, force_pairs: bool = False):
    """Sorted grouping for the dedup-first embedding update: returns
    (order, sf, sv, run_start) where ``order`` sorts the flattened (f, v)
    element list lexicographically, ``sf``/``sv`` are the sorted index
    pairs, and ``run_start`` marks each duplicate group's first element.

    Two equivalent paths: flat int32 keys (one argsort — the fast common
    case) while F*V fits int32, and a lexicographic (f, v) pair sort
    beyond that — int32 flat keys would silently WRAP for F*V > 2^31,
    merging unrelated rows into one dedup group and corrupting their
    updates, and int64 keys are unavailable with x64 disabled. Both sorts
    are stable over the same total order (v < vocab), so they produce the
    identical permutation (pinned in tests/test_model.py)."""
    if force_pairs:
        order = jnp.lexsort((v_flat, f_flat))
    else:
        order = jnp.argsort(v_flat + f_flat * vocab)
    sf = f_flat[order]
    sv = v_flat[order]
    run_start = jnp.concatenate(
        [jnp.ones((1,), bool), (sf[1:] != sf[:-1]) | (sv[1:] != sv[:-1])]
    )
    return order, sf, sv, run_start


def sparse_train_step(
    params,
    opt_state: SparseEmbOptState,
    batch,
    cfg: DLRMConfig,
    tx,
    embed_lr: float = 0.01,
    embed_eps: float = 1e-8,
):
    """One train step with SPARSE embedding updates (row-wise AdaGrad).

    The table gradient never materializes: the loss is differentiated
    w.r.t. the GATHERED rows [B, F, D] (gather is linear, so scatter-adding
    the row gradients reproduces the dense table gradient exactly), and
    only the touched rows are updated. Per-step embedding traffic is
    O(B·F·D) instead of O(F·V·D) — at Criteo scale (V=2^20, D=64) that is
    ~100MB instead of ~7GB per step, which is what makes large-vocab DLRM
    training feasible at all (the reference's TensorFlow consumers get the
    same effect from tf.IndexedSlices).

    Embedding rule: row-wise AdaGrad (the industry-standard DLRM choice —
    one accumulator per ROW, not per element), with DEDUP-FIRST duplicate
    semantics: indices repeated inside a batch first sum their row
    gradients, then the accumulator adds mean((sum g)^2) ONCE per unique
    row — exactly what dense row-wise AdaGrad on the full table gradient
    does (and what TF IndexedSlices consumers / torchrec do). The dedup is
    a sort + segment-sum over the B*F (feature, row) keys — O(B*F log)
    on-device, trivial next to the table gather/scatter — with each unique
    row's single contribution split evenly over its duplicates so plain
    scatter-adds apply it exactly once. Non-embedding params go through
    the wrapped optax transform unchanged.

    Jit this whole function (donate params + opt_state)."""
    table = params["embeddings"]                            # [F, V, D]
    idx = batch["cat"]                                      # [B, F]
    f_ix = jnp.arange(cfg.num_categorical)[None, :]         # [1, F]
    rows = table[f_ix, idx]                                 # [B, F, D]
    dense_params = {k: v for k, v in params.items() if k != "embeddings"}

    def loss_of(dp, r):
        return loss_fn(dp, batch, cfg, emb=r)

    loss, (g_dense, g_rows) = jax.value_and_grad(loss_of, argnums=(0, 1))(
        dense_params, rows
    )
    updates, new_dense_state = tx.update(g_dense, opt_state.dense, dense_params)
    dense_params = jax.tree.map(lambda p, u: p + u, dense_params, updates)
    g_rows = g_rows.astype(jnp.float32)
    fdim, vocab = cfg.num_categorical, cfg.vocab_size
    d = g_rows.shape[-1]
    n = idx.shape[0] * fdim
    f_flat = jnp.broadcast_to(f_ix, idx.shape).reshape(n)   # [N] feature id
    v_flat = idx.reshape(n)                                 # [N] vocab row
    order, sf, sv, run_start = _dedup_sort(
        f_flat, v_flat, vocab, force_pairs=fdim * vocab > _FLAT_KEY_MAX
    )
    sg = g_rows.reshape(n, d)[order]
    rid = jnp.cumsum(run_start) - 1                         # run id per element
    # per-element view of its duplicate group's summed gradient and size
    g_sum = jax.ops.segment_sum(
        sg, rid, num_segments=n, indices_are_sorted=True
    )[rid]                                                  # [N, D]
    m = jax.ops.segment_sum(
        jnp.ones((n,), jnp.float32), rid, num_segments=n, indices_are_sorted=True
    )[rid]                                                  # [N]
    inv_m = 1.0 / m
    ms_share = jnp.mean(g_sum * g_sum, axis=-1) * inv_m     # sums to mean(G^2)
    # Scatter with (f, v) index PAIRS, never a flattened [F*V] view: the
    # table/accum keep their [F, V@model, D] layout, so GSPMD scatters into
    # the model-sharded V axis instead of all-gathering a reshaped table
    # (both _dedup_sort paths emit (f, v) in lexicographic order).
    accum = opt_state.accum.at[sf, sv].add(ms_share, indices_are_sorted=True)
    # post-accumulation scale, shared by a row's duplicates by construction
    scale = embed_lr * jax.lax.rsqrt(accum[sf, sv] + embed_eps)     # [N]
    table = table.at[sf, sv].add(
        -(scale * inv_m)[:, None] * g_sum, indices_are_sorted=True
    )
    params = dict(dense_params, embeddings=table)
    return params, SparseEmbOptState(new_dense_state, accum), loss


# ---------------------------------------------------------------------------
# Shardings
# ---------------------------------------------------------------------------


def param_shardings(mesh: Mesh, params, model_axis: str = "model"):
    """Tensor-parallel layout: embedding tables sharded over the vocab dim,
    MLP hidden dims sharded over 'model', biases/small tensors replicated."""
    has_model = model_axis in mesh.shape and mesh.shape[model_axis] > 1

    axis_size = mesh.shape.get(model_axis, 1)

    def spec_of(path: Tuple[str, ...], leaf) -> NamedSharding:
        if not has_model:
            return NamedSharding(mesh, P())
        name = "/".join(str(p) for p in path)
        if name.startswith("embeddings") and leaf.shape[1] % axis_size == 0:
            return NamedSharding(mesh, P(None, model_axis, None))  # [F, V@model, D]
        if name.startswith("embeddings"):
            return NamedSharding(mesh, P())
        if leaf.ndim == 2 and leaf.shape[1] % axis_size == 0:
            return NamedSharding(mesh, P(None, model_axis))        # [in, out@model]
        return NamedSharding(mesh, P())

    def walk(tree, path=()):
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        if isinstance(tree, list):
            return [walk(v, path + (i,)) for i, v in enumerate(tree)]
        return spec_of(path, tree)

    return walk(params)


def batch_shardings(mesh: Mesh, batch, data_axis: str = "data", seq_axis: Optional[str] = None):
    """Batch dim on 'data'; optionally the sequence (L) dim of 3-D features
    on a 'seq' axis — sequence/context parallelism for long sequences."""
    out = {}
    for name, arr in batch.items():
        if arr.ndim >= 2 and seq_axis and name == "frames" and seq_axis in mesh.shape:
            out[name] = NamedSharding(mesh, P(data_axis, seq_axis, *([None] * (arr.ndim - 2))))
        else:
            out[name] = NamedSharding(mesh, P(data_axis, *([None] * (arr.ndim - 1))))
    return out


def make_synthetic_batch(
    cfg: DLRMConfig, batch_size: int, seed: int = 0
) -> Dict[str, np.ndarray]:
    """Deterministic synthetic Criteo-like host batch (numpy, host-side)."""
    rng = np.random.default_rng(seed)
    batch = {
        "dense": rng.normal(size=(batch_size, cfg.num_dense)).astype(np.float32),
        "cat": rng.integers(
            0, cfg.vocab_size, size=(batch_size, cfg.num_categorical), dtype=np.int64
        ),
        "label": rng.integers(0, 2, size=(batch_size,)).astype(np.float32),
    }
    if cfg.seq_len:
        batch["frames"] = rng.normal(
            size=(batch_size, cfg.seq_len, cfg.seq_dim)
        ).astype(np.float32)
        batch["frames_len"] = rng.integers(
            1, cfg.seq_len + 1, size=(batch_size,)
        ).astype(np.int32)
    return batch
