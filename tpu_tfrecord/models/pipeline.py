"""Pipeline parallelism (PP) over a mesh axis: GPipe-style microbatching
with GSPMD-style INTERLEAVED VIRTUAL STAGES, scale-shaped, plus a
microbatch-streamed serving mode.

The reference framework has no model-side parallelism (SURVEY.md §2) — this
is the PP member of the consumer-model family, completing the dp/tp/sp/ep/pp
set the mesh design supports (dlrm: dp×tp×sp, attention: sp, moe: ep).

TPU-idiomatic construction (the collective-permute pipeline from the
public scaling playbook, jax-ml.github.io/scaling-book — NOT a torch-style
send/recv scheduler), rebuilt so every per-device quantity scales with the
SHARD, not the global tensor (GSPMD's contract, PAPERS.md):

- `shard_map` over the ``pipe`` axis; each device holds ONE stage's
  parameters (the stacked [S, ...] stage pytree is sharded on its leading
  dim, so stage weights never replicate — that is what makes it PP).
- **interleaved virtual stages** (``n_virtual=V`` > 1, GSPMD / Megatron
  interleaving, arxiv 2105.04663): stage weights stack ``[S, V, ...]`` and
  device d owns V ROUND-ROBIN chunks of the layer sequence — virtual
  stages d, d+S, d+2S, … Each compute tick applies ONE chunk (1/V of the
  device's layers), and the schedule visits chunks in the interleaved
  order, so a microbatch re-enters stage 0 after each lap of the ring.
  Warmup shrinks by ~V: the bubble falls from (S-1)/(M+S-1) to
  (S-1)/(V·M+S-1) — measured, not assumed, by the per-tick occupancy
  counter below. The interleaving costs nothing structural: virtual stage
  k runs on device k mod S, so consecutive virtual stages are ALWAYS one
  forward ring hop apart (including the S-1 → 0 wrap onto the next
  virtual slot) and the same three ppermute rings carry the schedule.
- the microbatch tensor is SHARDED on the pipe axis too: device d holds
  only its block of ceil(M/S) microbatches, never the full [M, mb, ...]
  stream (the old construction replicated it to every stage, so per-device
  input memory grew with M and defeated the point of pipelining).
- the stream enters at stage 0 only, via a FEED RING: one microbatch slice
  per device rotates one hop toward stage 0 each tick (`lax.ppermute`),
  timed so microbatch m arrives at stage 0 exactly at its injection tick
  inj(m) = (m // S)·V·S + (m mod S) (for V=1, inj(m)=m — the classic
  schedule). In-flight input per device is ONE [mb, ...] slice — O(mb),
  constant in M and V.
- activations hop device s -> s+1 with `lax.ppermute` each tick; M
  microbatches flow through S·V virtual stages in V·M + S - 1 compute
  ticks inside one `lax.fori_loop` (static trip count -> one compiled
  program, reverse-mode differentiable via scan).
- outputs are born on the LAST stage's LAST virtual chunk and ride an OUT
  RING (one more O(mb) ppermute per tick) back to the device that owns
  that microbatch's output shard — a targeted permute, not the old `psum`
  broadcast that replicated the full [M, mb, ...] result to every device.
  A trailing S - 1 permute-only drain delivers the final in-flight
  outputs without extra stage compute.

Per-device totals: input ceil(M/S)·mb (the shard), loop state 3 slices +
the output shard, collectives 3 ppermutes of ONE slice per tick. The
compiled HLO therefore contains collective-permutes of microbatch-slice
size only — no all-gather, no all-reduce — pinned by the
tools/graftlint/hlo_contracts manifest (plain, dp-composed, interleaved,
and streaming rows).

`pipeline_apply` is the sharded entry point; `pipeline_reference` is the
sequential oracle used by the tests. `microbatch_sharding` gives callers
the input layout so the stream can be device_put straight into its shard
(feeding the pipeline never materializes [M, mb, ...] anywhere).
`PipelineStream` is the SERVING mode: a persistent jitted per-tick step
whose feed is exactly one [mb, ...] slice — microbatches stream through
the same rings one request at a time, outputs pop with pipeline latency,
and no M-deep stream exists anywhere (the per-call argument is the pin).
"""

from __future__ import annotations

import collections
import functools
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_tfrecord.models._compat import shard_map

StageFn = Callable[[Any, jax.Array], jax.Array]


def _stage_count(stage_params: Any, n_virtual: int) -> int:
    leaves = jax.tree.leaves(stage_params)
    if not leaves:
        raise ValueError("stage_params has no leaves")
    if n_virtual > 1 and any(
        l.ndim < 2 or l.shape[1] != n_virtual for l in leaves
    ):
        bad = [l.shape for l in leaves if l.ndim < 2 or l.shape[1] != n_virtual]
        raise ValueError(
            f"n_virtual={n_virtual} needs stage_params leaves stacked "
            f"[S, V, ...]; offending leaf shapes: {bad}"
        )
    return leaves[0].shape[0]


def pipeline_reference(
    stage_fn: StageFn, stage_params: Any, xs: jax.Array, n_virtual: int = 1
) -> jax.Array:
    """Sequential oracle: fold every microbatch through all S·V virtual
    stages in interleaved order (virtual stage k = v·S + s runs chunk v of
    device s). stage_params: pytree stacked on a leading S dim ([S, V, ...]
    when ``n_virtual`` > 1); xs: [M, mb, ...]."""
    n_stages = _stage_count(stage_params, n_virtual)

    def one(x):
        for v in range(n_virtual):
            for s in range(n_stages):
                if n_virtual == 1:
                    params_c = jax.tree.map(lambda a: a[s], stage_params)
                else:
                    params_c = jax.tree.map(
                        lambda a: a[s, v], stage_params  # noqa: B023
                    )
                x = stage_fn(params_c, x)
        return x

    return jax.vmap(one)(xs)


def microbatch_sharding(
    mesh: Mesh, pipe_axis: str = "pipe", ndim: Any = 3,
    batch_spec: P = P(),
) -> NamedSharding:
    """Input layout for ``pipeline_apply``: microbatch dim 0 sharded on the
    pipe axis (device d holds its ceil(M/S) block), trailing dims per
    ``batch_spec``. device_put the stream with this so no device ever
    materializes the full [M, mb, ...] tensor. Needs M % S == 0 (pad the
    stream first when it does not divide — `pipeline_apply` only pads
    internally for inputs that arrive unsharded).

    ``ndim`` is the stream's rank — pass either the int or the stream
    array itself (anything with an ``.ndim``), so call sites stop
    hand-threading ``ndim=xs.ndim``."""
    nd = int(getattr(ndim, "ndim", ndim))
    tail = tuple(batch_spec) + (None,) * (nd - 1 - len(tuple(batch_spec)))
    return NamedSharding(mesh, P(pipe_axis, *tail))


def _chunk_params(params, v_idx, n_virtual: int):
    """This tick's chunk of the local [V, ...] stage stack: static for the
    classic V=1 schedule (the exact pre-interleaving program), a
    differentiable dynamic_index for V>1."""
    if n_virtual == 1:
        return params
    return jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(a, v_idx, keepdims=False),
        params,
    )


def _schedule_decode(u, s, n_stages: int, n_virtual: int):
    """THE per-tick schedule decode — (v_idx, chunk0, last_chunk) for
    per-device step u = t - s: which virtual chunk this device applies,
    whether it is virtual stage 0 (eats the feed) and whether it is the
    LAST virtual stage (births an output). Shared by the batch loop and
    the serving tick, so the streamed-vs-batch bitwise contract cannot
    drift from a one-sided edit. V=1 keeps the static predicates of the
    pre-interleaving program."""
    if n_virtual == 1:
        return None, s == 0, s == n_stages - 1
    v_idx = jax.lax.rem(
        jnp.maximum(u, 0) // n_stages, n_virtual
    ).astype(jnp.int32)
    return (
        v_idx,
        (s == 0) & (v_idx == 0),
        (s == n_stages - 1) & (v_idx == n_virtual - 1),
    )


def _pipeline_local(
    params_stk, xs_local, *, stage_fn: StageFn, n_micro: int, n_stages: int,
    n_virtual: int, block: int, axis: str, diagnostics: bool = False,
):
    """Per-device body (inside shard_map): params_stk is THIS stage's slice
    (leading dim 1; [1, V, ...] when interleaved); xs_local is THIS
    device's [R, mb, ...] block of the microbatch stream (R = ceil(M/S);
    device d owns microbatches [d*R, (d+1)*R)).

    Per-device schedule: local step u = t - s walks (round r, chunk v,
    offset i) in the interleaved order u = r·V·S + v·S + i — microbatch
    m = r·S + i, virtual chunk v. Every chunk's input is the activation
    produced ONE tick earlier ONE ring hop back (virtual stage k = v·S + s
    runs on device k mod S, so both the intra-lap hop s -> s+1 and the
    lap wrap S-1 -> 0 are a single forward permute) — the V=1 dataflow,
    unchanged; only the weights indexed per tick and the injection /
    birth timing generalize.

    Three O(mb) rings, all ppermute:
      feed ring (hop -1): device d injects its slice for microbatch m at
        tick inj(m) - d (inj(m) = (m // S)·V·S + m mod S), so it reaches
        stage 0 exactly when chunk 0 of m is due. Invariant: at tick t,
        device j's feed slot holds the microbatch whose inj is t + j.
      activation ring (hop +1): a chunk's output becomes the next virtual
        stage's input.
      out ring (hop +1): the last stage injects each microbatch finishing
        its LAST chunk (v = V-1); the owner (m // R) captures it into its
        output shard. Invariant: at tick t device j holds the output
        injected at tick t - ((j+1) mod S).

    ``diagnostics`` (static flag) additionally threads a per-tick
    occupancy counter through the loop carry: device s's compute at tick
    t is USEFUL iff its local step u = t - s decodes to a real microbatch
    (u >= 0 and m(u) < n_micro — the same predicate the capture mask
    enforces; warmup/drain ticks compute garbage and count as bubble).
    The counter measures the occupancy of THIS compiled schedule's loop,
    tick by tick — so the interleaved schedule reports its own number
    instead of someone re-deriving a closed form. For V=1 it equals
    (S-1)/(M+S-1) exactly and for the interleaved schedule
    (S-1)/(V·M+S-1) (both pinned by tests); it is identical on every
    device, so no collective is needed and the gather-free HLO pin
    survives with the flag on.
    """
    params = jax.tree.map(lambda a: a[0], params_stk)
    s = jax.lax.axis_index(axis)
    r_blk = block
    vs = n_stages * n_virtual
    mb_shape = xs_local.shape[1:]
    fwd = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    back = [(j, (j - 1) % n_stages) for j in range(n_stages)]
    zero = jnp.zeros(mb_shape, xs_local.dtype)
    feed0, act0, ring0 = zero, zero, zero
    outbuf0 = jnp.zeros((r_blk,) + mb_shape, xs_local.dtype)

    def m_of(u):
        # microbatch index of per-device step u = r·V·S + v·S + i:
        # m = r·S + i. jnp // floors, so negative u lands at m < 0, which
        # every consumer masks out (occupancy and capture both require a
        # real microbatch index).
        if n_virtual == 1:
            return u
        return (u // vs) * n_stages + jax.lax.rem(
            jnp.maximum(u, 0), n_stages
        )

    def capture(t, ring, outbuf):
        # device j holds the output injected at tick t - ((j+1) mod S);
        # that output was born when device S-1 finished step
        # u_o = (injection tick) - (S-1), which is a BIRTH step only when
        # its chunk is the last (u_o mod V·S >= (V-1)·S); capture it iff j
        # owns that microbatch's output shard
        ti = t - jax.lax.rem(s + 1, n_stages)
        u_o = ti - (n_stages - 1)
        if n_virtual == 1:
            m_cap = u_o
            born = m_cap >= 0
        else:
            born = (u_o >= 0) & (
                jax.lax.rem(u_o, vs) >= vs - n_stages
            )
            m_cap = m_of(u_o)
        cap = born & (m_cap >= 0) & (m_cap < n_micro) & (m_cap // r_blk == s)
        slot = jnp.clip(m_cap - s * r_blk, 0, r_blk - 1)
        got = jax.lax.dynamic_index_in_dim(outbuf, slot, keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(
            outbuf, jnp.where(cap, ring, got), slot, axis=0
        )

    def tick(t, state):
        feed, act, ring, outbuf, useful = state
        u = t - s
        v_idx, chunk0, last_chunk = _schedule_decode(
            u, s, n_stages, n_virtual
        )
        # feed ring: rotate toward stage 0, then inject this device's
        # next owned slice the moment its travel time is due. The slot at
        # (t, j) carries the microbatch with inj(m) = t + j; a = t + s
        # decodes to a real injection slot iff a mod V·S < S
        a = t + s
        if n_virtual == 1:
            m_inj = a
            slot_ok = True
        else:
            in_round = jax.lax.rem(a, vs)
            slot_ok = in_round < n_stages
            m_inj = (a // vs) * n_stages + in_round
        inject = slot_ok & (m_inj < n_micro) & (m_inj // r_blk == s)
        local_r = jnp.clip(m_inj - s * r_blk, 0, r_blk - 1)
        mine = jax.lax.dynamic_index_in_dim(xs_local, local_r, keepdims=False)
        feed = jnp.where(inject, mine, jax.lax.ppermute(feed, axis, back))
        # stage compute: chunk (v=0, s=0) eats the feed, every other
        # virtual stage the arriving activation (clipped reads past M
        # compute garbage that the capture mask never collects)
        out = stage_fn(
            _chunk_params(params, v_idx, n_virtual),
            jnp.where(chunk0, feed, act),
        )
        # out ring: rotate, the last virtual stage injects its finished
        # microbatch
        ring = jnp.where(
            last_chunk, out, jax.lax.ppermute(ring, axis, fwd)
        )
        outbuf = capture(t, ring, outbuf)
        act = jax.lax.ppermute(out, axis, fwd)  # hop to the next stage
        if diagnostics:
            # this tick computed chunk step u; useful iff its microbatch
            # is real
            useful = useful + jnp.where(
                (u >= 0) & (m_of(u) < n_micro), 1.0, 0.0
            ).astype(jnp.float32)
        return feed, act, ring, outbuf, useful

    def drain(t, state):
        # permute-only tail: the last S - 1 in-flight outputs finish their
        # ring journey; no stage compute, no feed
        ring, outbuf = state
        ring = jax.lax.ppermute(ring, axis, fwd)
        outbuf = capture(t, ring, outbuf)
        return ring, outbuf

    # the last real microbatch's final chunk is born on device S-1 at step
    # u_last; the main loop must run THROUGH that birth tick
    r_last, i_last = (n_micro - 1) // n_stages, (n_micro - 1) % n_stages
    u_last = r_last * vs + (n_virtual - 1) * n_stages + i_last
    t_end = u_last + n_stages  # exclusive: birth tick u_last + S - 1
    _, _, ring, outbuf, useful = jax.lax.fori_loop(
        0, t_end, tick,
        (feed0, act0, ring0, outbuf0, jnp.float32(0.0)),
    )
    if n_stages > 1:
        _, outbuf = jax.lax.fori_loop(
            t_end, t_end + n_stages - 1, drain,
            (ring, outbuf),
        )
    if not diagnostics:
        return outbuf
    total = jnp.float32(t_end)
    useful = jax.lax.stop_gradient(useful)
    diag = {
        "bubble_fraction": 1.0 - useful / total,
        "useful_ticks": useful,
        "total_ticks": total,
    }
    if n_virtual > 1:
        diag["virtual_stages"] = jnp.float32(n_virtual)
    return outbuf, diag


def pipeline_apply(
    stage_fn: StageFn,
    stage_params: Any,
    xs: jax.Array,
    mesh: Mesh,
    pipe_axis: str = "pipe",
    batch_spec: P = P(),
    n_virtual: int = 1,
    param_spec: Any = None,
    diagnostics: bool = False,
):
    """Run M microbatches through S pipeline stages sharded on
    ``mesh[pipe_axis]`` — optionally S·V interleaved virtual stages.

    stage_params: pytree whose leaves are stacked [S, ...] (S = axis
    size), or [S, V, ...] with ``n_virtual=V`` > 1 — device d then owns
    the V round-robin virtual stages d, d+S, …, each a chunk the schedule
    applies on its own tick; every stage must map shape [mb, ...] ->
    [mb, ...] (same shape, so the activation hop is shape-stable).
    xs: [M, mb, ...]. Returns [M, mb, ...], bitwise the sequential
    composition (pinned by tests) for any V.

    Scale shape: xs is consumed SHARDED on the pipe axis (block layout —
    device d holds microbatches [d*R, (d+1)*R), R = ceil(M/S); see
    `microbatch_sharding`), so per-device input is the shard, the
    in-flight feed is one [mb, ...] slice, and every collective moves one
    slice — in M and in V.

    ``batch_spec`` optionally shards the PER-MICROBATCH dims over further
    mesh axes (e.g. ``P('data')`` to keep the mb dim data-parallel inside
    the pipeline — the dp×pp composition); stage_fn then sees its
    (pipe, data)-local block and may itself use collectives over those
    axes, which are manual inside the same shard_map (models.moe's
    ``moe_ep_body`` composes EP under a pipe×V×expert mesh this way).

    ``param_spec`` optionally gives the stage_params pytree per-leaf
    PartitionSpecs (each must lead with ``pipe_axis``) so stage weights
    can shard FURTHER axes — e.g. the expert dim of an MoE stage on the
    expert axis. Default: every leaf P(pipe_axis).

    ``diagnostics`` (static flag) returns (out, diag) where diag carries
    the bubble as THIS compiled schedule's loop pays it:
    ``bubble_fraction`` (idle compute ticks / total, counted per tick
    from the schedule's own occupancy predicate, so a rebuilt schedule
    reports its own number — (S-1)/(M+S-1) for the classic V=1 schedule,
    (S-1)/(V·M+S-1) interleaved, both pinned by tests),
    ``useful_ticks``, ``total_ticks``, and (V>1) ``virtual_stages`` — f32
    scalars, identical on every device (no collective added: the HLO
    stays gather-free).
    """
    if n_virtual < 1:
        raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
    n_stages = mesh.shape[pipe_axis]
    leaves = jax.tree.leaves(stage_params)
    if not leaves or any(l.shape[0] != n_stages for l in leaves):
        bad = [l.shape for l in leaves if l.shape[0] != n_stages]
        raise ValueError(
            f"stage_params leaves must stack {n_stages} stages on the "
            f"leading dim (mesh['{pipe_axis}']); offending leaf shapes: "
            f"{bad or 'no leaves'}"
        )
    _stage_count(stage_params, n_virtual)  # validates the [S, V, ...] stack
    n_micro = xs.shape[0]
    block = -(-n_micro // n_stages)  # ceil: each device's owned slice count
    padded = block * n_stages
    if padded != n_micro:
        # pad the stream so the block layout divides; padded microbatches
        # compute garbage the capture mask never collects
        xs = jnp.concatenate(
            [xs, jnp.zeros((padded - n_micro,) + xs.shape[1:], xs.dtype)]
        )
    tail = tuple(batch_spec) + (None,) * (xs.ndim - 1 - len(tuple(batch_spec)))
    spec = P(pipe_axis, *tail)
    if param_spec is None:
        param_spec = P(pipe_axis)
    else:
        # a spec not leading with the pipe axis would hand every device
        # the FULL stage stack and _pipeline_local's [0]-slice would
        # silently run stage 0's weights everywhere — reject loudly
        # is_leaf must also catch None: tree.leaves would silently DROP
        # None entries, and shard_map reads None as replicated — the
        # exact silent-wrong-weights case this guard exists to reject
        for p_leaf in jax.tree.leaves(
            param_spec, is_leaf=lambda x: x is None or isinstance(x, P)
        ):
            entries = tuple(p_leaf) if p_leaf is not None else ()
            if not entries or entries[0] != pipe_axis:
                raise ValueError(
                    f"param_spec leaves must lead with the pipe axis "
                    f"{pipe_axis!r} (stage weights shard on it); got "
                    f"{p_leaf}"
                )
    diag_spec = {
        "bubble_fraction": P(), "useful_ticks": P(), "total_ticks": P(),
    }
    if n_virtual > 1:
        diag_spec["virtual_stages"] = P()
    fn = shard_map(
        functools.partial(
            _pipeline_local, stage_fn=stage_fn, n_micro=n_micro,
            n_stages=n_stages, n_virtual=n_virtual, block=block,
            axis=pipe_axis, diagnostics=diagnostics,
        ),
        mesh=mesh,
        in_specs=(param_spec, spec),
        out_specs=(spec, diag_spec) if diagnostics else spec,
    )
    if diagnostics:
        out, diag = fn(stage_params, xs)
        return (out[:n_micro] if padded != n_micro else out), diag
    out = fn(stage_params, xs)
    return out[:n_micro] if padded != n_micro else out


# ---------------------------------------------------------------------------
# Microbatch-streamed serving mode
# ---------------------------------------------------------------------------


def _stream_tick_local(
    params_stk, t, act_l, x, *, stage_fn: StageFn, n_stages: int,
    n_virtual: int, axis: str,
):
    """One schedule tick of the SERVING pipeline (inside shard_map).

    The same interleaved schedule as `_pipeline_local`, with the host as
    the microbatch owner: the per-call feed is ONE replicated [mb, ...]
    slice delivered at stage 0 with zero travel time (the degenerate feed
    ring — the host injects at the consumption tick, so no transport hops
    are needed), and outputs are read straight off the last stage's lane
    of the stacked return instead of riding the out ring home (the host
    IS home). The activation ring is bit-identical to the batch
    schedule's, which is why streamed outputs equal batch-mode
    `pipeline_apply` BITWISE (pinned by tests)."""
    params = jax.tree.map(lambda a: a[0], params_stk)
    s = jax.lax.axis_index(axis)
    act = act_l[0]
    u = t - s
    v_idx, chunk0, _last = _schedule_decode(u, s, n_stages, n_virtual)
    out = stage_fn(
        _chunk_params(params, v_idx, n_virtual),
        jnp.where(chunk0, x, act),
    )
    fwd = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    act_next = jax.lax.ppermute(out, axis, fwd)
    return act_next[None], out[None]


class PipelineStream:
    """Microbatch-streamed inference over the pipelined stages: the
    heavy-traffic serving mode (ROADMAP #2).

    One persistent jitted per-tick step; each `push` feeds exactly ONE
    [mb, ...] slice (the compiled step's only data argument — no
    [M, mb, ...] stream is ever materialized, host- or device-side;
    pinned via the compiled argument bytes) and advances the schedule to
    that microbatch's injection slot. Outputs pop in FIFO order with the
    pipeline's latency (S·V ticks): in steady state within a round, one
    push is one tick and one completed microbatch pops per push. `flush`
    drains the tail microbatches after the last push.

    Stage weights and schedule are shared with `pipeline_apply`
    (``[S, ...]``, or ``[S, V, ...]`` interleaved) and streamed outputs
    are BITWISE equal to the batch mode on the same slices — the serving
    path cannot drift from the trained graph.
    """

    def __init__(
        self,
        stage_fn: StageFn,
        stage_params: Any,
        mesh: Mesh,
        pipe_axis: str = "pipe",
        n_virtual: int = 1,
        microbatch_shape: Optional[Tuple[int, ...]] = None,
        dtype: Any = jnp.float32,
    ):
        if n_virtual < 1:
            raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
        self._mesh = mesh
        self._axis = pipe_axis
        self._n_stages = mesh.shape[pipe_axis]
        self._n_virtual = n_virtual
        if _stage_count(stage_params, n_virtual) != self._n_stages:
            raise ValueError(
                f"stage_params must stack {self._n_stages} stages "
                f"(mesh['{pipe_axis}'])"
            )
        self._params = stage_params
        self._vs = self._n_stages * n_virtual
        self._step = jax.jit(
            shard_map(
                functools.partial(
                    _stream_tick_local, stage_fn=stage_fn,
                    n_stages=self._n_stages, n_virtual=n_virtual,
                    axis=pipe_axis,
                ),
                mesh=mesh,
                in_specs=(P(pipe_axis), P(), P(pipe_axis), P()),
                out_specs=(P(pipe_axis), P(pipe_axis)),
            )
        )
        self._dtype = dtype
        self._mb_shape: Optional[Tuple[int, ...]] = (
            tuple(microbatch_shape) if microbatch_shape is not None else None
        )
        self.served = 0  # microbatches whose outputs have been returned
        self.reset()

    # -- state ---------------------------------------------------------------

    def reset(self) -> None:
        """Forget all in-flight microbatches and restart at tick 0 (the
        compiled step survives — warmup pays compilation once)."""
        self._t = 0
        self._m = 0
        self._pending: collections.deque = collections.deque()
        self._act = None
        self._zeros = None
        if self._mb_shape is not None:
            self._ensure_state(self._mb_shape, self._dtype)

    def _ensure_state(self, mb_shape, dtype) -> None:
        if self._act is not None:
            if tuple(mb_shape) != self._mb_shape or np.dtype(
                dtype
            ) != np.dtype(self._dtype):
                raise ValueError(
                    f"microbatch {tuple(mb_shape)}/{np.dtype(dtype)} != "
                    f"the stream's {self._mb_shape}/"
                    f"{np.dtype(self._dtype)} (one compiled step, one "
                    f"shape, one dtype)"
                )
            return
        self._mb_shape = tuple(mb_shape)
        self._dtype = dtype
        self._act = jax.device_put(
            jnp.zeros((self._n_stages,) + self._mb_shape, dtype),
            NamedSharding(self._mesh, P(self._axis)),
        )
        self._zeros = jnp.zeros(self._mb_shape, dtype)

    def step_spec(self):
        """(jitted step fn, example args) for the HLO contract manifest —
        the compiled program every `push` runs. Requires the microbatch
        shape (pass ``microbatch_shape`` at construction or push once)."""
        if self._act is None:
            raise ValueError(
                "stream state not initialized: pass microbatch_shape to "
                "the constructor (or push once) before step_spec()"
            )
        return self._step, (
            self._params, jnp.int32(self._t), self._act, self._zeros
        )

    # -- schedule ------------------------------------------------------------

    def _inj(self, m: int) -> int:
        return (m // self._n_stages) * self._vs + m % self._n_stages

    def _tick(self, x, ready: List[Tuple[jax.Array, Any]]) -> None:
        # the host owns the tick counter (self._t); the device step takes
        # it as a plain traced scalar each call
        head = self._pending[0][1] if self._pending else None
        self._act, out = self._step(
            self._params, jnp.int32(self._t), self._act, x
        )
        if head is not None and self._t == head:
            # this tick finished the oldest in-flight microbatch's last
            # chunk on the last stage: its output is that device's lane.
            # Returned DEVICE-resident so downstream jits (e.g. the LM
            # head) consume it without a host round trip — callers that
            # want host bytes np.asarray it themselves
            _, _, tag = self._pending.popleft()
            ready.append((out[self._n_stages - 1], tag))
            self.served += 1
        self._t += 1

    def push(self, x) -> List[jax.Array]:
        """Inject one [mb, ...] microbatch and advance the schedule to its
        injection slot; returns the device-resident outputs (FIFO order)
        that completed along the way — usually one per push once the
        pipeline is full, none during warmup."""
        return [out for out, _ in self.push_tagged(x)]

    def push_tagged(self, x, tag: Any = None) -> List[Tuple[jax.Array, Any]]:
        """`push` that rides an opaque host-side tag on the microbatch's
        FIFO entry and returns ``(output, tag)`` pairs. The tag never
        enters the compiled step (the per-call argument-bytes pin is
        unchanged) — it exists so a multiplexer (the serving tier) can map
        popped outputs back to the requests packed into each slot."""
        x = jnp.asarray(x)
        self._ensure_state(x.shape, x.dtype)
        # next injection slot the clock has not passed yet: a flush (or
        # any idle drain) advances the tick counter, so the schedule
        # re-bases onto the first usable slot — skipped slots just
        # compute garbage on their own diagonals, which nothing collects
        m = self._m
        while self._inj(m) < self._t:
            m += 1
        inj = self._inj(m)
        # birth tick of m's last chunk on the last stage: inj + S·V - 1
        self._pending.append((m, inj + self._vs - 1, tag))
        self._m = m + 1
        ready: List[Tuple[jax.Array, Any]] = []
        while self._t < inj:
            self._tick(self._zeros, ready)   # gap ticks between rounds
        self._tick(x, ready)                 # the injection tick itself
        return ready

    def flush(self) -> List[jax.Array]:
        """Drain: run permute/compute ticks (zero feed) until every pushed
        microbatch's output has popped; returns them in FIFO order."""
        return [out for out, _ in self.flush_tagged()]

    def flush_tagged(self) -> List[Tuple[jax.Array, Any]]:
        """`flush` returning ``(output, tag)`` pairs (see `push_tagged`)."""
        ready: List[Tuple[jax.Array, Any]] = []
        while self._pending:
            self._tick(self._zeros, ready)
        return ready

    @property
    def in_flight(self) -> int:
        return len(self._pending)
