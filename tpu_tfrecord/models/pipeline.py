"""Pipeline parallelism (PP) over a mesh axis: GPipe-style microbatching.

The reference framework has no model-side parallelism (SURVEY.md §2) — this
is the PP member of the consumer-model family, completing the dp/tp/sp/ep/pp
set the mesh design supports (dlrm: dp×tp×sp, attention: sp, moe: ep).

TPU-idiomatic construction (the collective-permute pipeline from the
public scaling playbook, jax-ml.github.io/scaling-book — NOT a torch-style
send/recv scheduler):
- `shard_map` over the ``pipe`` axis; each device holds ONE stage's
  parameters (the stacked [S, ...] stage pytree is sharded on its leading
  dim, so stage weights never replicate — that is what makes it PP).
- M microbatches flow through S stages in M + S - 1 ticks inside one
  `lax.fori_loop` (static trip count → one compiled program, reverse-mode
  differentiable via scan); activations hop device s -> s+1 with
  `lax.ppermute` each tick, riding neighbor ICI links.
- the classic bubble: S - 1 of the ticks per device are idle warmup/drain.
  Efficiency = M / (M + S - 1) — callers pick M accordingly.
- outputs accumulate on the last stage and replicate with one `psum`
  (devices other than the last contribute zeros).

`pipeline_apply` is the sharded entry point; `pipeline_reference` is the
sequential oracle used by the tests.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

StageFn = Callable[[Any, jax.Array], jax.Array]


def pipeline_reference(stage_fn: StageFn, stage_params: Any, xs: jax.Array) -> jax.Array:
    """Sequential oracle: fold every microbatch through all S stages.
    stage_params: pytree stacked on a leading S dim; xs: [M, mb, ...]."""
    n_stages = jax.tree.leaves(stage_params)[0].shape[0]

    def one(x):
        for s in range(n_stages):
            params_s = jax.tree.map(lambda a: a[s], stage_params)
            x = stage_fn(params_s, x)
        return x

    return jax.vmap(one)(xs)


def _pipeline_local(params_stk, xs, *, stage_fn: StageFn, n_micro: int, axis: str):
    """Per-device body (inside shard_map): params_stk is THIS stage's slice
    (leading dim 1); xs is the full replicated [M, mb, ...] input."""
    params = jax.tree.map(lambda a: a[0], params_stk)
    s = jax.lax.axis_index(axis)
    n_stages = jax.lax.axis_size(axis)
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    mb_shape = xs.shape[1:]
    # the loop writes device-varying values into these, so their types must
    # be pipe-varying from the start (xs is replicated -> unvarying)
    carry0 = jax.lax.pcast(jnp.zeros(mb_shape, xs.dtype), (axis,), to="varying")
    out0 = jax.lax.pcast(
        jnp.zeros((n_micro,) + mb_shape, xs.dtype), (axis,), to="varying"
    )

    def tick(t, state):
        carry, outbuf = state
        # stage 0 injects microbatch t (clipped reads past M compute
        # garbage that the output mask below never collects)
        inp = jnp.where(s == 0, xs[jnp.clip(t, 0, n_micro - 1)], carry)
        out = stage_fn(params, inp)
        m = t - (n_stages - 1)  # the microbatch the LAST stage just finished
        write = (s == n_stages - 1) & (m >= 0)
        mc = jnp.clip(m, 0, n_micro - 1)
        outbuf = outbuf.at[mc].set(jnp.where(write, out, outbuf[mc]))
        carry = jax.lax.ppermute(out, axis, perm)  # hop to the next stage
        return carry, outbuf

    _, outbuf = jax.lax.fori_loop(
        0, n_micro + n_stages - 1, tick, (carry0, out0)
    )
    # only the last stage wrote; psum replicates the result everywhere
    return jax.lax.psum(outbuf, axis)


def pipeline_apply(
    stage_fn: StageFn,
    stage_params: Any,
    xs: jax.Array,
    mesh: Mesh,
    pipe_axis: str = "pipe",
) -> jax.Array:
    """Run M microbatches through S pipeline stages sharded on
    ``mesh[pipe_axis]``.

    stage_params: pytree whose leaves are stacked [S, ...] (S = axis size);
    every stage must map shape [mb, ...] -> [mb, ...] (same shape, so the
    activation hop is shape-stable). xs: [M, mb, ...]. Returns [M, mb, ...],
    bitwise the sequential composition (pinned by tests).
    """
    n_stages = mesh.shape[pipe_axis]
    leaves = jax.tree.leaves(stage_params)
    if not leaves or any(l.shape[0] != n_stages for l in leaves):
        bad = [l.shape for l in leaves if l.shape[0] != n_stages]
        raise ValueError(
            f"stage_params leaves must stack {n_stages} stages on the "
            f"leading dim (mesh['{pipe_axis}']); offending leaf shapes: "
            f"{bad or 'no leaves'}"
        )
    n_micro = xs.shape[0]
    fn = jax.shard_map(
        functools.partial(
            _pipeline_local, stage_fn=stage_fn, n_micro=n_micro, axis=pipe_axis
        ),
        mesh=mesh,
        in_specs=(P(pipe_axis), P()),
        out_specs=P(),
    )
    return fn(stage_params, xs)
