"""Pipeline parallelism (PP) over a mesh axis: GPipe-style microbatching,
scale-shaped.

The reference framework has no model-side parallelism (SURVEY.md §2) — this
is the PP member of the consumer-model family, completing the dp/tp/sp/ep/pp
set the mesh design supports (dlrm: dp×tp×sp, attention: sp, moe: ep).

TPU-idiomatic construction (the collective-permute pipeline from the
public scaling playbook, jax-ml.github.io/scaling-book — NOT a torch-style
send/recv scheduler), rebuilt so every per-device quantity scales with the
SHARD, not the global tensor (GSPMD's contract, PAPERS.md):

- `shard_map` over the ``pipe`` axis; each device holds ONE stage's
  parameters (the stacked [S, ...] stage pytree is sharded on its leading
  dim, so stage weights never replicate — that is what makes it PP).
- the microbatch tensor is SHARDED on the pipe axis too: device d holds
  only its block of ceil(M/S) microbatches, never the full [M, mb, ...]
  stream (the old construction replicated it to every stage, so per-device
  input memory grew with M and defeated the point of pipelining).
- the stream enters at stage 0 only, via a FEED RING: one microbatch slice
  per device rotates one hop toward stage 0 each tick (`lax.ppermute`),
  timed so microbatch t arrives at stage 0 exactly at tick t. In-flight
  input per device is ONE [mb, ...] slice — O(mb), constant in M.
- activations hop device s -> s+1 with `lax.ppermute` each tick; M
  microbatches flow through S stages in M + S - 1 compute ticks inside one
  `lax.fori_loop` (static trip count -> one compiled program, reverse-mode
  differentiable via scan).
- outputs are born on the LAST stage and ride an OUT RING (one more
  O(mb) ppermute per tick) back to the device that owns that microbatch's
  output shard — a targeted permute, not the old `psum` broadcast that
  replicated the full [M, mb, ...] result to every device. A trailing
  S - 1 permute-only drain delivers the final in-flight outputs without
  extra stage compute.
- the classic bubble is unchanged: S - 1 of the compute ticks per device
  are idle warmup/drain. Efficiency = M / (M + S - 1) — callers pick M.

Per-device totals: input ceil(M/S)·mb (the shard), loop state 3 slices +
the output shard, collectives 3 ppermutes of ONE slice per tick. The
compiled HLO therefore contains collective-permutes of microbatch-slice
size only — no all-gather, no all-reduce — pinned by tests/hlo_util.

`pipeline_apply` is the sharded entry point; `pipeline_reference` is the
sequential oracle used by the tests. `microbatch_sharding` gives callers
the input layout so the stream can be device_put straight into its shard
(feeding the pipeline never materializes [M, mb, ...] anywhere).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_tfrecord.models._compat import shard_map

StageFn = Callable[[Any, jax.Array], jax.Array]


def pipeline_reference(stage_fn: StageFn, stage_params: Any, xs: jax.Array) -> jax.Array:
    """Sequential oracle: fold every microbatch through all S stages.
    stage_params: pytree stacked on a leading S dim; xs: [M, mb, ...]."""
    n_stages = jax.tree.leaves(stage_params)[0].shape[0]

    def one(x):
        for s in range(n_stages):
            params_s = jax.tree.map(lambda a: a[s], stage_params)
            x = stage_fn(params_s, x)
        return x

    return jax.vmap(one)(xs)


def microbatch_sharding(
    mesh: Mesh, pipe_axis: str = "pipe", ndim: int = 3,
    batch_spec: P = P(),
) -> NamedSharding:
    """Input layout for ``pipeline_apply``: microbatch dim 0 sharded on the
    pipe axis (device d holds its ceil(M/S) block), trailing dims per
    ``batch_spec``. device_put the stream with this so no device ever
    materializes the full [M, mb, ...] tensor. Needs M % S == 0 (pad the
    stream first when it does not divide — `pipeline_apply` only pads
    internally for inputs that arrive unsharded)."""
    tail = tuple(batch_spec) + (None,) * (ndim - 1 - len(tuple(batch_spec)))
    return NamedSharding(mesh, P(pipe_axis, *tail))


def _pipeline_local(
    params_stk, xs_local, *, stage_fn: StageFn, n_micro: int, n_stages: int,
    block: int, axis: str, diagnostics: bool = False,
):
    """Per-device body (inside shard_map): params_stk is THIS stage's slice
    (leading dim 1); xs_local is THIS device's [R, mb, ...] block of the
    microbatch stream (R = ceil(M/S); device d owns microbatches
    [d*R, (d+1)*R)).

    Three O(mb) rings, all ppermute:
      feed ring (hop -1): device d injects its slice for microbatch m at
        tick m - d, so it reaches stage 0 exactly at tick m. Invariant:
        at tick t, device j's feed slot holds microbatch t + j.
      activation ring (hop +1): stage s's output becomes stage s+1's input.
      out ring (hop +1): the last stage injects each finished microbatch;
        the owner (m // R) captures it ((m+1 thru S-1)-hop journey later)
        into its output shard. Invariant: at tick t device j holds the
        output injected at tick t - ((j+1) mod S).

    ``diagnostics`` (static flag) additionally threads a per-tick
    occupancy counter through the loop carry: stage s's compute at tick t
    is USEFUL iff its microbatch m = t - s is real (0 <= m < n_micro —
    the same predicate the capture mask enforces; warmup/drain ticks
    compute garbage and count as bubble). The counter measures the
    occupancy of THIS compiled schedule's loop, tick by tick — so a
    rebuilt schedule (interleaved virtual stages, a different trip
    count) changes the number automatically instead of someone
    re-deriving a closed form. For this 1F1B construction it equals
    (S-1)/(M+S-1) exactly (pinned by tests); it is identical on every
    device, so no collective is needed and the gather-free HLO pin
    survives with the flag on.
    """
    params = jax.tree.map(lambda a: a[0], params_stk)
    s = jax.lax.axis_index(axis)
    r_blk = block
    mb_shape = xs_local.shape[1:]
    fwd = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    back = [(j, (j - 1) % n_stages) for j in range(n_stages)]
    zero = jnp.zeros(mb_shape, xs_local.dtype)
    feed0, act0, ring0 = zero, zero, zero
    outbuf0 = jnp.zeros((r_blk,) + mb_shape, xs_local.dtype)

    def capture(t, ring, outbuf):
        # device j holds the output injected at tick t - ((j+1) mod S),
        # i.e. microbatch  t - ((j+1) mod S) - (S-1); capture it iff j
        # owns that microbatch's output shard
        m_cap = t - jax.lax.rem(s + 1, n_stages) - (n_stages - 1)
        cap = (m_cap >= 0) & (m_cap < n_micro) & (m_cap // r_blk == s)
        slot = jnp.clip(m_cap - s * r_blk, 0, r_blk - 1)
        got = jax.lax.dynamic_index_in_dim(outbuf, slot, keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(
            outbuf, jnp.where(cap, ring, got), slot, axis=0
        )

    def tick(t, state):
        feed, act, ring, outbuf, useful = state
        # feed ring: rotate toward stage 0, then inject this device's
        # next owned slice (m = t + s) the moment its travel time is due
        m_inj = t + s
        inject = (m_inj < n_micro) & (m_inj // r_blk == s)
        local_r = jnp.clip(m_inj - s * r_blk, 0, r_blk - 1)
        mine = jax.lax.dynamic_index_in_dim(xs_local, local_r, keepdims=False)
        feed = jnp.where(inject, mine, jax.lax.ppermute(feed, axis, back))
        # stage compute: stage 0 eats the feed, everyone else the arriving
        # activation (clipped reads past M compute garbage that the
        # capture mask never collects)
        out = stage_fn(params, jnp.where(s == 0, feed, act))
        # out ring: rotate, last stage injects its finished microbatch
        ring = jnp.where(
            s == n_stages - 1, out, jax.lax.ppermute(ring, axis, fwd)
        )
        outbuf = capture(t, ring, outbuf)
        act = jax.lax.ppermute(out, axis, fwd)  # hop to the next stage
        if diagnostics:
            # this tick computed microbatch m = t - s; useful iff real
            m = t - s
            useful = useful + jnp.where(
                (m >= 0) & (m < n_micro), 1.0, 0.0
            ).astype(jnp.float32)
        return feed, act, ring, outbuf, useful

    def drain(t, state):
        # permute-only tail: the last S - 1 in-flight outputs finish their
        # ring journey; no stage compute, no feed
        ring, outbuf = state
        ring = jax.lax.ppermute(ring, axis, fwd)
        outbuf = capture(t, ring, outbuf)
        return ring, outbuf

    _, _, ring, outbuf, useful = jax.lax.fori_loop(
        0, n_micro + n_stages - 1, tick,
        (feed0, act0, ring0, outbuf0, jnp.float32(0.0)),
    )
    if n_stages > 1:
        _, outbuf = jax.lax.fori_loop(
            n_micro + n_stages - 1, n_micro + 2 * n_stages - 2, drain,
            (ring, outbuf),
        )
    if not diagnostics:
        return outbuf
    total = jnp.float32(n_micro + n_stages - 1)
    useful = jax.lax.stop_gradient(useful)
    return outbuf, {
        "bubble_fraction": 1.0 - useful / total,
        "useful_ticks": useful,
        "total_ticks": total,
    }


def pipeline_apply(
    stage_fn: StageFn,
    stage_params: Any,
    xs: jax.Array,
    mesh: Mesh,
    pipe_axis: str = "pipe",
    batch_spec: P = P(),
    diagnostics: bool = False,
):
    """Run M microbatches through S pipeline stages sharded on
    ``mesh[pipe_axis]``.

    stage_params: pytree whose leaves are stacked [S, ...] (S = axis size);
    every stage must map shape [mb, ...] -> [mb, ...] (same shape, so the
    activation hop is shape-stable). xs: [M, mb, ...]. Returns [M, mb, ...],
    bitwise the sequential composition (pinned by tests).

    Scale shape: xs is consumed SHARDED on the pipe axis (block layout —
    device d holds microbatches [d*R, (d+1)*R), R = ceil(M/S); see
    `microbatch_sharding`), so per-device input is the shard, the in-flight
    feed is one [mb, ...] slice, and every collective moves one slice.

    ``batch_spec`` optionally shards the PER-MICROBATCH dims over further
    mesh axes (e.g. ``P('data')`` to keep the mb dim data-parallel inside
    the pipeline — the dp×pp composition); stage_fn then sees its
    (pipe, data)-local block and may itself use collectives over those
    axes, which are manual inside the same shard_map.

    ``diagnostics`` (static flag) returns (out, diag) where diag carries
    the bubble as THIS compiled schedule's loop pays it:
    ``bubble_fraction`` (idle compute ticks / (M + S - 1) total, counted
    per tick from the schedule's own occupancy predicate, so a rebuilt
    schedule reports its own number — for 1F1B it equals the analytic
    (S-1)/(M+S-1), pinned by tests; the baseline ROADMAP #2's
    interleaved-V schedules must shrink), ``useful_ticks``,
    ``total_ticks`` — f32 scalars, identical on every device (no
    collective added: the HLO stays gather-free).
    """
    n_stages = mesh.shape[pipe_axis]
    leaves = jax.tree.leaves(stage_params)
    if not leaves or any(l.shape[0] != n_stages for l in leaves):
        bad = [l.shape for l in leaves if l.shape[0] != n_stages]
        raise ValueError(
            f"stage_params leaves must stack {n_stages} stages on the "
            f"leading dim (mesh['{pipe_axis}']); offending leaf shapes: "
            f"{bad or 'no leaves'}"
        )
    n_micro = xs.shape[0]
    block = -(-n_micro // n_stages)  # ceil: each device's owned slice count
    padded = block * n_stages
    if padded != n_micro:
        # pad the stream so the block layout divides; padded microbatches
        # compute garbage the capture mask never collects
        xs = jnp.concatenate(
            [xs, jnp.zeros((padded - n_micro,) + xs.shape[1:], xs.dtype)]
        )
    tail = tuple(batch_spec) + (None,) * (xs.ndim - 1 - len(tuple(batch_spec)))
    spec = P(pipe_axis, *tail)
    diag_spec = {
        "bubble_fraction": P(), "useful_ticks": P(), "total_ticks": P(),
    }
    fn = shard_map(
        functools.partial(
            _pipeline_local, stage_fn=stage_fn, n_micro=n_micro,
            n_stages=n_stages, block=block, axis=pipe_axis,
            diagnostics=diagnostics,
        ),
        mesh=mesh,
        in_specs=(P(pipe_axis), spec),
        out_specs=(spec, diag_spec) if diagnostics else spec,
    )
    if diagnostics:
        out, diag = fn(stage_params, xs)
        return (out[:n_micro] if padded != n_micro else out), diag
    out = fn(stage_params, xs)
    return out[:n_micro] if padded != n_micro else out
