"""Elastic service layer: the decode fleet tracks offered load.

The tf.data service paper (PAPERS.md, "A Case for Disaggregating ML Input
Data Processing") argues disaggregation only pays for itself through
*autoscaling* and *sharing*; PR 8's service held worker count fixed and
served exactly one job. This module is the autoscaling half (the sharing
half — tenant-keyed leasing and the fleet-wide warm cache — lives in
service.py): a **FleetScaler** that closes the loop between the cluster
flight recorder and the dispatcher's worker fleet.

The control loop is the autotuner's (PR 6) lifted one level up:

- **Sensor**: the PR 7 ``TelemetryAggregator`` merges every consumer
  process's spool into one cluster verdict — ``producer_bound`` (the
  trainers' prefetch queues are starved: decode capacity is the
  bottleneck) or ``consumer_bound`` (queues full: decode capacity is
  wasted) — over ALIVE processes only. No running consumer at all reads
  as ``idle`` (offered load is zero).
- **Actuator**: the dispatcher. Scale-up SPAWNS a decode-worker process
  (``spawn`` callable — ``subprocess_spawner`` in production, an
  in-process factory in tests/bench). Scale-down picks a victim
  deterministically (last in sorted order among the active workers) and
  marks it **draining** via ``ServiceDispatcher.drain``: its unstarted
  leases are handed back for re-routing, new shards route around it, it
  finishes whatever streams it is serving, says a clean goodbye (the
  ``goodbye`` op; its telemetry spool lands a ``final: true`` snapshot),
  and exits. A victim SIGKILLed mid-drain is indistinguishable from any
  other dead worker: its heartbeat expires and consumers re-route with
  exactly-once dedupe.
- **Guard rails**: the same ``BoundedClimber`` hysteresis + cooldown the
  per-iterator controller uses (tpu_tfrecord.autotune) — chaos-injected
  stalls flip the verdict tick to tick, and a flapping verdict must
  never whipsaw the fleet. Spawns in flight count against the ceiling
  (``pending``) so a slow registration can't trigger a spawn storm.

Determinism is the contract carried over from PR 8: every consumer's
byte stream is identical across ANY resize, because shard ownership is
consumer-tracked (acked offsets + redelivered-prefix dedupe) and the
per-shard route merely picks WHO decodes — never what is decoded.

Since the HA PR (ISSUE 17) the lease space is *partitioned* across K
dispatchers, and one scaler federates over all of them: ``dispatcher``
may be a list (local objects and/or ``DispatcherHandle`` remote
proxies). The census merges every partition's books deduping by
worker id (a worker registers with EVERY partition); a drain victim is
drained on every partition; and — the failover whipsaw guard — if ANY
partition's status is unreadable the tick is non-actionable
(``elastic.census_errors``): a fleet mid-failover is never resized on a
partial view.

Counters (in the scaler/dispatcher process): ``elastic.scale_ups``
(spawn decisions), ``elastic.scale_downs`` (drain decisions),
``elastic.drains`` (drains completed — goodbye received),
``elastic.drained_leases`` (leases handed back at drain),
``elastic.spawn_errors``, ``elastic.census_errors`` (a partition's
status was unreadable — tick skipped). Gauge: ``elastic.workers``.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from tpu_tfrecord import telemetry
from tpu_tfrecord.autotune import BoundedClimber
from tpu_tfrecord.metrics import METRICS, logger

__all__ = [
    "ScalerPolicy",
    "FleetScaler",
    "ServingScaler",
    "ServingReplicaSpawner",
    "DispatcherHandle",
    "SubprocessSpawner",
    "subprocess_spawner",
]

#: Scaler decision cadence when the caller sets none.
DEFAULT_INTERVAL_S = 1.0


@dataclass
class ScalerPolicy:
    """Bounds and pacing for the fleet-level hill-climber. The fleet only
    moves after ``hysteresis`` consecutive same-verdict ticks and at most
    once per ``cooldown_s`` wall-clock window (the whipsaw guard); worker
    count is clamped to [min_workers, max_workers]; a spawn that has not
    registered within ``pending_timeout_s`` stops counting against the
    ceiling (the process died at exec — retrying is allowed again)."""

    hysteresis: int = 2
    cooldown_s: float = 5.0
    min_workers: int = 1
    max_workers: int = 8
    pending_timeout_s: float = 30.0

    def __post_init__(self) -> None:
        if self.min_workers < 1:
            raise ValueError("min_workers must be >= 1")
        if self.max_workers < self.min_workers:
            raise ValueError("max_workers must be >= min_workers")
        if self.hysteresis < 1:
            raise ValueError("hysteresis must be >= 1")


class FleetScaler:
    """Fleet-level bounded hill-climbing over the decode-worker count.

    One scaler per FLEET — it is the only thing that spawns or drains
    workers (two scalers over one fleet would fight). ``dispatcher`` is
    a single dispatcher (PR 12 shape) or, under partitioning, a list of
    one per partition — local ``ServiceDispatcher`` objects and/or
    ``DispatcherHandle`` proxies for partitions hosted elsewhere. The
    scaler's verdict block is published to every partition so
    ``serve-status`` shows it no matter which one is asked. ``step()``
    is one decision tick; pass ``interval_s`` and call ``start()`` for
    the production thread, or drive ``step()`` directly with an injected
    clock in tests.

    The verdict source is either a spool directory (a
    ``fleet.TelemetryAggregator`` is built over it) or an injected
    ``aggregator`` object with the same ``aggregate()`` shape — the test
    seam. ``roles`` optionally scopes the verdict to specific telemetry
    roles (e.g. only ``trainer`` processes) via the aggregator's role
    filter.
    """

    def __init__(
        self,
        dispatcher,
        spawn: Callable[[], Any],
        spool_dir: Optional[str] = None,
        aggregator=None,
        policy: Optional[ScalerPolicy] = None,
        interval_s: float = DEFAULT_INTERVAL_S,
        roles: Optional[List[str]] = None,
        trace_id: Optional[str] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if (spool_dir is None) == (aggregator is None):
            raise ValueError(
                "exactly one of spool_dir / aggregator must be given"
            )
        if aggregator is None:
            from tpu_tfrecord import fleet

            aggregator = fleet.TelemetryAggregator(
                spool_dir, trace_id=trace_id
            )
        if isinstance(dispatcher, (list, tuple)):
            if not dispatcher:
                raise ValueError("dispatcher list must be non-empty")
            self.dispatchers = list(dispatcher)
        else:
            self.dispatchers = [dispatcher]
        #: partition 0, kept for the PR 12 single-dispatcher surface
        self.dispatcher = self.dispatchers[0]
        self.spawn = spawn
        self.aggregator = aggregator
        self.policy = policy or ScalerPolicy()
        self.interval_s = float(interval_s)
        self.roles = list(roles) if roles is not None else None
        self.clock = clock
        self._climber = BoundedClimber(
            self.policy.hysteresis,
            self.policy.cooldown_s,
            clock=clock,
            # "idle" (no running consumer) is a shrink signal the
            # per-iterator controller never sees: zero offered load means
            # the fleet should coast at min_workers
            actionable=("producer_bound", "consumer_bound", "idle"),
        )
        #: full decision log, same shape discipline as AutotuneController
        self.log: List[Dict[str, Any]] = []
        self.last_decision: Optional[Dict[str, Any]] = None
        self._tick = 0
        self._pending: List[float] = []  # spawn times not yet registered
        self._known_ids: set = set()
        self._last_verdict: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # surface ourselves on every partition's status() page
        self._publish(self.status(workers=0, draining=[]))

    # -- census ----------------------------------------------------------------

    def _publish(self, st: Dict[str, Any]) -> None:
        """Push the scaler block onto every partition's status page (a
        plain attribute set locally; one ``scaler_status`` RPC through a
        ``DispatcherHandle``). A partition unreachable right now —
        mid-failover — just misses one refresh; the next tick re-pushes."""
        for d in self.dispatchers:
            try:
                d.scaler_status = st
            except OSError as e:
                logger.warning(
                    "tfrecord.elastic scaler-status publish failed: %s", e
                )

    def _census(self) -> Optional[Dict[str, Any]]:
        """Who is in the fleet right now, merged over every partition's
        books (workers register with ALL partitions — dedupe by worker
        id; a worker is active if any partition sees it alive and
        undraining, draining if any partition has it marked): active,
        draining, and the pending spawns that have not registered yet.

        Returns None when ANY partition's status is unreadable: during a
        failover window one partition's books are in transit between
        primary and standby, and a census over the remaining partitions
        would double-count or miss workers — the whipsaw the climber's
        hysteresis cannot see. The tick is skipped instead
        (``elastic.census_errors``)."""
        statuses = []
        for i, d in enumerate(self.dispatchers):
            try:
                statuses.append(d.status())
            except (OSError, RuntimeError) as e:
                METRICS.count("elastic.census_errors")
                logger.warning(
                    "tfrecord.elastic census blind: partition %d "
                    "unreadable (%s)", i, e
                )
                return None
        seen: Dict[str, Dict[str, Any]] = {}
        for st in statuses:
            for w in st["workers"]:
                prev = seen.setdefault(
                    w["worker_id"], {"alive": False, "draining": False}
                )
                prev["alive"] = prev["alive"] or bool(w["alive"])
                prev["draining"] = prev["draining"] or bool(w.get("draining"))
        ids = set(seen)
        # registrations observed since the last tick retire pending spawns
        for _ in ids - self._known_ids:
            if self._pending:
                self._pending.pop(0)
        self._known_ids = ids
        now = self.clock()
        self._pending = [
            t for t in self._pending
            if now - t < self.policy.pending_timeout_s
        ]
        active = sorted(
            wid for wid, w in seen.items()
            if w["alive"] and not w["draining"]
        )
        draining = sorted(
            wid for wid, w in seen.items()
            if w["alive"] and w["draining"]
        )
        return {"active": active, "draining": draining, "statuses": statuses}

    def _verdict(self) -> str:
        """Cluster verdict over the alive, still-running consumers; no
        such process at all = ``idle`` (load removed or never offered)."""
        try:
            snap = self.aggregator.aggregate(roles=self.roles)
        except FileNotFoundError:
            # spool dir not created yet (no consumer has ever spooled):
            # indistinguishable from zero offered load
            return "idle"
        except OSError as e:
            # any OTHER read failure (EACCES, EIO, an NFS hiccup) is an
            # unreadable fleet, not an idle one — the aggregator's own
            # invariant. Non-actionable: the tick is skipped, a loaded
            # fleet is never drained on blindness.
            METRICS.count("elastic.verdict_errors")
            logger.warning("tfrecord.elastic verdict unreadable: %s", e)
            return "unreadable"
        running = [
            p for p in snap.alive
            if not p.final and telemetry.OCCUPANCY_GAUGE in p.gauges
        ]
        if not running:
            return "idle"
        return snap.verdict

    # -- the decision tick -----------------------------------------------------

    def step(self) -> Optional[Dict[str, Any]]:
        """One control step: read the verdict, apply at most one fleet
        move (spawn or drain), update the dispatcher's scaler status.
        Returns the decision dict when a move was made, else None."""
        self._tick += 1
        pol = self.policy
        census = self._census()
        if census is None:
            # a partition is unreadable (failover in flight): the fleet
            # view is partial, so neither the climber nor the floor
            # check may act on it — and the stale published verdict is
            # left in place rather than replaced with a blind one
            return None
        active, draining = census["active"], census["draining"]
        effective = len(active) + len(self._pending)
        verdict = self._verdict()
        self._last_verdict = verdict
        decision: Optional[Dict[str, Any]] = None
        if effective < pol.min_workers:
            # below the floor is not a hill-climbing question — refill
            # immediately (dead workers, a fleet coming up from zero)
            decision = self._spawn_one(effective, "below_min")
        else:
            act = self._climber.observe(verdict)
            if act == "producer_bound" and effective < pol.max_workers:
                decision = self._spawn_one(effective, act)
                if decision is not None:
                    self._climber.acted()
            elif act in ("consumer_bound", "idle") and len(active) > pol.min_workers:
                decision = self._drain_one(active, act)
                if decision is not None:
                    self._climber.acted()
        METRICS.gauge("elastic.workers", float(len(active)))
        self._publish(self.status(workers=len(active), draining=draining))
        return decision

    def _spawn_one(self, effective: int, reason: str) -> Optional[Dict[str, Any]]:
        try:
            self.spawn()
        except Exception as e:  # noqa: BLE001 — a failed exec must not
            # kill the control loop; the next tick retries
            METRICS.count("elastic.spawn_errors")
            logger.warning("tfrecord.elastic spawn failed: %s", e)
            return None
        self._pending.append(self.clock())
        METRICS.count("elastic.scale_ups")
        return self._record("scale_up", reason, {"workers": effective,
                                                 "target": effective + 1})

    def _drain_one(self, active: List[str], reason: str) -> Optional[Dict[str, Any]]:
        # deterministic victim: the LAST worker in sorted id order — the
        # same pick on every replay of the same fleet state, and (because
        # routing interleaves over the sorted alive list) the one whose
        # removal perturbs the fewest existing assignments
        victim = active[-1]
        # the victim holds leases on EVERY partition that routed work to
        # it — each must hand them back; "drained" if any partition knew
        # the worker at all (partitions that never routed to it answer
        # False harmlessly)
        drained = False
        for i, d in enumerate(self.dispatchers):
            try:
                drained = bool(d.drain(victim)) or drained
            except OSError as e:
                logger.warning(
                    "tfrecord.elastic drain of %s on partition %d "
                    "failed: %s", victim, i, e
                )
        if not drained:
            return None
        METRICS.count("elastic.scale_downs")
        return self._record("scale_down", reason, {"workers": len(active),
                                                   "target": len(active) - 1,
                                                   "victim": victim})

    def _record(self, action: str, reason: str, extra: Dict[str, Any]) -> Dict[str, Any]:
        decision = {"tick": self._tick, "action": action, "reason": reason,
                    **extra}
        self.log.append(decision)
        self.last_decision = decision
        telemetry.instant("elastic.decision", action=action, reason=reason)
        return decision

    def status(self, workers: int, draining: List[str]) -> Dict[str, Any]:
        """The ``scaler`` block surfaced on the dispatcher's status page
        (and thus ``tfrecord_doctor serve-status``)."""
        return {
            "workers": workers,
            "draining": list(draining),
            "pending_spawns": len(self._pending),
            "min_workers": self.policy.min_workers,
            "max_workers": self.policy.max_workers,
            "verdict": self._last_verdict,
            "last_decision": self.last_decision,
            "scale_ups": METRICS.counter("elastic.scale_ups"),
            "scale_downs": METRICS.counter("elastic.scale_downs"),
            "drains_completed": METRICS.counter("elastic.drains"),
        }

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "FleetScaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tfr-fleet-scaler"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "FleetScaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — the control loop is
                # telemetry-adjacent: it must never die silently mid-fleet
                METRICS.count("elastic.step_errors")
                logger.warning("tfrecord.elastic step failed: %s", e)


class DispatcherHandle:
    """Remote-dispatcher proxy with exactly the surface ``FleetScaler``
    touches — ``status()``, ``drain()``, and ``scaler_status``
    assignment — so one scaler can federate over partitions it does not
    host in-process. ``addrs`` is one partition's member list in
    preference order (primary first, then its standby, i.e. one ``|``
    group of the partition-map spec): every RPC walks the list and a
    member answering ``not_primary`` (a standby, or a demoted zombie) is
    skipped for primary-only ops, so the handle follows a failover
    without reconfiguration."""

    def __init__(self, addrs, timeout: float = 5.0):
        if isinstance(addrs, str):
            addrs = [a.strip() for a in addrs.split("|") if a.strip()]
        if not addrs:
            raise ValueError("DispatcherHandle needs at least one address")
        self.addrs = [str(a) for a in addrs]
        self.timeout = float(timeout)
        self._scaler_status: Optional[Dict[str, Any]] = None

    def _rpc(self, msg: Dict[str, Any], primary_only: bool) -> Dict[str, Any]:
        from tpu_tfrecord import service as _service
        from tpu_tfrecord import service_protocol as sp

        last: Optional[BaseException] = None
        for addr in self.addrs:
            try:
                sock = sp.connect(addr, timeout=self.timeout)
                try:
                    sock.settimeout(self.timeout)
                    reply = sp.request(
                        sock, addr,
                        {**msg, "proto": _service.PROTO_VERSION},
                    )
                finally:
                    try:
                        sock.close()
                    except OSError:
                        pass
            except OSError as e:  # ProtocolError is a ConnectionError
                last = e
                continue
            if primary_only and reply.get("error") == "not_primary":
                last = OSError(f"{addr}: not primary")
                continue
            return reply
        raise OSError(
            f"no member of partition {self.addrs} answered: {last}"
        )

    def status(self) -> Dict[str, Any]:
        return self._rpc({"op": "status"}, primary_only=False)

    def drain(self, worker_id: str) -> bool:
        reply = self._rpc(
            {"op": "drain", "worker_id": str(worker_id)}, primary_only=True
        )
        return bool(reply.get("drained"))

    @property
    def scaler_status(self) -> Optional[Dict[str, Any]]:
        return self._scaler_status

    @scaler_status.setter
    def scaler_status(self, st: Optional[Dict[str, Any]]) -> None:
        # assignment IS the publish — mirrors the plain-attribute set on
        # a local ServiceDispatcher; OSError propagates for the caller
        # (FleetScaler._publish) to log
        self._scaler_status = st
        self._rpc({"op": "scaler_status", "status": st}, primary_only=False)


class SubprocessSpawner:
    """The production ``spawn``: each call launches one
    ``python -m tpu_tfrecord.service worker`` subprocess pointed at the
    dispatcher — ``dispatcher_addr`` may be a single ``host:port`` or a
    full partition-map spec (``h:p1|h:p2,h:p3``), which the worker
    parses to register with every partition — with any extra CLI args
    appended (``--cache``, ``--spool-dir``, ``--fault-plan`` for chaos
    replays, ...). Tracks its
    children so ``reap()`` can terminate whatever is still alive — a
    drained worker exits on its own; reap is the shutdown safety net."""

    def __init__(
        self,
        dispatcher_addr: str,
        extra_args: tuple = (),
        env: Optional[Dict[str, str]] = None,
    ):
        self.dispatcher_addr = str(dispatcher_addr)
        self.extra_args = tuple(str(a) for a in extra_args)
        self.env = dict(env) if env is not None else None
        self.procs: List[Any] = []
        self._lock = threading.Lock()

    def __call__(self):
        import subprocess
        import sys

        # keep the CALLER's cwd — relative dataset paths in job specs,
        # relative --spool-dir/--fault-plan worker args, etc. must
        # resolve exactly as they would for a manually started worker.
        # Importability of `-m tpu_tfrecord.service` is guaranteed by
        # prepending this package's parent to the child's PYTHONPATH
        # instead.
        env = dict(self.env) if self.env is not None else dict(os.environ)
        pkg_parent = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        env["PYTHONPATH"] = (
            pkg_parent + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else pkg_parent
        )
        p = subprocess.Popen(
            [sys.executable, "-m", "tpu_tfrecord.service", "worker",
             "--dispatcher", self.dispatcher_addr, *self.extra_args],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            env=env,
        )
        with self._lock:
            self.procs.append(p)
        return p

    def reap(self, timeout: float = 10.0) -> None:
        with self._lock:
            procs = list(self.procs)
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=timeout)
                except Exception:  # noqa: BLE001  # graftlint: swallow(best-effort shutdown reap; kill() fallback follows)
                    try:
                        p.kill()
                    except OSError:
                        pass


def subprocess_spawner(
    dispatcher_addr: str,
    extra_args: tuple = (),
    env: Optional[Dict[str, str]] = None,
) -> SubprocessSpawner:
    return SubprocessSpawner(dispatcher_addr, extra_args, env=env)


# ---------------------------------------------------------------------------
# Serving role (ISSUE 18): replicas scale on queue-depth/p99
# ---------------------------------------------------------------------------


def _serving_status_rpc(addr: str, timeout: float = 5.0) -> Dict[str, Any]:
    from tpu_tfrecord import service_protocol as sp

    sock = sp.connect(addr, timeout=timeout)
    try:
        sock.settimeout(timeout)
        return sp.request(
            sock, addr, {"v": sp.PROTO_VERSION, "op": "status", "req": 0}
        )
    finally:
        try:
            sock.close()
        except OSError:
            pass


def _serving_drain_rpc(addr: str, timeout: float = 5.0) -> Dict[str, Any]:
    from tpu_tfrecord import service_protocol as sp

    sock = sp.connect(addr, timeout=timeout)
    try:
        sock.settimeout(timeout)
        return sp.request(
            sock, addr, {"v": sp.PROTO_VERSION, "op": "drain", "req": 0}
        )
    finally:
        try:
            sock.close()
        except OSError:
            pass


class ServingScaler:
    """The serving-role twin of :class:`FleetScaler` (ISSUE 18): scales
    inference REPLICAS (``tpu_tfrecord.serving`` servers) on
    queue-depth/p99 the way the decode fleet scales on producer_bound.

    - **Sensor**: each replica's ``status`` RPC (queue depth, in-flight,
      per-request p99, completion counter). The fleet verdict is the
      worst replica's `telemetry.serving_verdict` — ``queue_bound``
      (requests queue faster than slots free: add a replica), or —
      when every replica is empty AND no request completed since the
      last tick — ``idle`` (drain one). ``meeting_slo``/
      ``compute_bound`` hold the size: more replicas cannot speed up
      the compiled step itself.
    - **Actuator**: ``spawn()`` must launch a replica and return its
      address once it is ready to serve (the SubprocessServingSpawner
      shape: block on the child's ready line). Scale-down picks the
      LAST active address in sorted order and sends the ``drain`` RPC:
      the replica stops admitting, finishes in-flight requests, lands
      its ``final: true`` spool snapshot, and exits; its disappearance
      retires it from the member list (``elastic.drains``).
    - **Guard rails**: the same ``BoundedClimber`` hysteresis/cooldown.
      A replica that stops answering WITHOUT having been drained — a
      SIGKILL — is dropped from the membership immediately, and the
      ``min_workers`` floor refills it outside the climber (the same
      below-floor bypass the decode fleet uses); meanwhile clients walk
      the member list, so the dead replica's queue drains through the
      survivors.

    ``step()`` is one decision tick (drive it directly with an injected
    clock in tests); ``start()`` runs the production thread.
    """

    def __init__(
        self,
        spawn: Callable[[], str],
        replicas: Optional[List[str]] = None,
        policy: Optional[ScalerPolicy] = None,
        interval_s: float = DEFAULT_INTERVAL_S,
        status_fn: Callable[[str], Dict[str, Any]] = _serving_status_rpc,
        drain_fn: Callable[[str], Dict[str, Any]] = _serving_drain_rpc,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.spawn = spawn
        self.replicas: List[str] = list(replicas or [])
        self.policy = policy or ScalerPolicy()
        self.interval_s = float(interval_s)
        self._status = status_fn
        self._drain = drain_fn
        self.clock = clock
        self._climber = BoundedClimber(
            self.policy.hysteresis,
            self.policy.cooldown_s,
            clock=clock,
            actionable=("queue_bound", "idle"),
        )
        self.log: List[Dict[str, Any]] = []
        self.last_decision: Optional[Dict[str, Any]] = None
        self._tick = 0
        self._draining: set = set()
        self._last_completed: Optional[int] = None
        self._last_verdict: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- census ----------------------------------------------------------------

    def _census(self) -> Dict[str, Any]:
        """Poll every member: active statuses, replicas mid-drain, and —
        unlike the decode fleet's partition census — DEAD members, which
        are actionable here: a drained replica saying goodbye retires
        cleanly (``elastic.drains``) while an undrained death is a kill
        the floor check must refill."""
        statuses: Dict[str, Dict[str, Any]] = {}
        for addr in list(self.replicas):
            try:
                st = self._status(addr)
            except (OSError, RuntimeError) as e:
                self.replicas.remove(addr)
                if addr in self._draining:
                    self._draining.discard(addr)
                    METRICS.count("elastic.drains")
                else:
                    METRICS.count("elastic.replicas_lost")
                    logger.warning(
                        "tfrecord.elastic serving replica %s lost "
                        "undrained: %s", addr, e
                    )
                continue
            statuses[addr] = st
            if st.get("draining"):
                self._draining.add(addr)
        active = sorted(a for a in statuses if a not in self._draining)
        return {"active": active, "statuses": statuses}

    def _verdict(self, census: Dict[str, Any]) -> str:
        """Worst replica wins; idleness needs BOTH empty queues and zero
        completions since the last tick (a fleet at exactly its capacity
        has empty queues between bursts — that is not idle)."""
        active = census["active"]
        if not active:
            return "unknown"
        statuses = [census["statuses"][a] for a in active]
        completed = sum(int(s.get("completed") or 0) for s in statuses)
        delta = (
            None if self._last_completed is None
            else completed - self._last_completed
        )
        self._last_completed = completed
        backlog = sum(
            int(s.get("queue_depth") or 0) + int(s.get("in_flight") or 0)
            for s in statuses
        )
        if backlog == 0 and delta == 0:
            return "idle"
        worst = "unknown"
        rank = {"meeting_slo": 1, "compute_bound": 2, "queue_bound": 3}
        for s in statuses:
            v = telemetry.serving_verdict(
                s.get("p99_ms"), s.get("queue_depth"),
                float(s.get("slo_p99_ms") or 0.0) or 250.0,
                max_queue=int(s.get("max_queue") or 16),
            )
            if rank.get(v, 0) > rank.get(worst, 0):
                worst = v
        return worst

    # -- the decision tick -----------------------------------------------------

    def step(self) -> Optional[Dict[str, Any]]:
        """One control step: census, verdict, at most one fleet move.
        Below-floor refill (dead replica) bypasses the climber — a
        SIGKILLed replica is replaced on the next tick, not after
        ``hysteresis`` of them."""
        self._tick += 1
        pol = self.policy
        census = self._census()
        active = census["active"]
        verdict = self._verdict(census)
        self._last_verdict = verdict
        decision: Optional[Dict[str, Any]] = None
        if len(active) < pol.min_workers:
            decision = self._spawn_one(len(active), "below_min")
        else:
            act = self._climber.observe(verdict)
            if act == "queue_bound" and len(active) < pol.max_workers:
                decision = self._spawn_one(len(active), act)
                if decision is not None:
                    self._climber.acted()
            elif act == "idle" and len(active) > pol.min_workers:
                decision = self._drain_one(active, act)
                if decision is not None:
                    self._climber.acted()
        METRICS.gauge("elastic.replicas", float(len(census["active"])))
        return decision

    def _spawn_one(self, n: int, reason: str) -> Optional[Dict[str, Any]]:
        try:
            addr = self.spawn()
        except Exception as e:  # noqa: BLE001 — a failed exec must not
            # kill the control loop; the next tick retries
            METRICS.count("elastic.spawn_errors")
            logger.warning("tfrecord.elastic serving spawn failed: %s", e)
            return None
        self.replicas.append(str(addr))
        METRICS.count("elastic.scale_ups")
        return self._record("scale_up", reason,
                            {"replicas": n, "target": n + 1,
                             "addr": str(addr)})

    def _drain_one(self, active: List[str], reason: str) -> Optional[Dict[str, Any]]:
        victim = active[-1]
        try:
            self._drain(victim)
        except OSError as e:
            logger.warning(
                "tfrecord.elastic drain of serving replica %s failed: %s",
                victim, e,
            )
            return None
        self._draining.add(victim)
        METRICS.count("elastic.scale_downs")
        return self._record("scale_down", reason,
                            {"replicas": len(active),
                             "target": len(active) - 1, "victim": victim})

    def _record(self, action: str, reason: str, extra: Dict[str, Any]) -> Dict[str, Any]:
        decision = {"tick": self._tick, "action": action, "reason": reason,
                    **extra}
        self.log.append(decision)
        self.last_decision = decision
        telemetry.instant("elastic.decision", action=action, reason=reason)
        return decision

    def status(self) -> Dict[str, Any]:
        return {
            "replicas": list(self.replicas),
            "draining": sorted(self._draining),
            "min_workers": self.policy.min_workers,
            "max_workers": self.policy.max_workers,
            "verdict": self._last_verdict,
            "last_decision": self.last_decision,
            "scale_ups": METRICS.counter("elastic.scale_ups"),
            "scale_downs": METRICS.counter("elastic.scale_downs"),
            "drains_completed": METRICS.counter("elastic.drains"),
        }

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "ServingScaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tfr-serving-scaler"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "ServingScaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — the control loop is
                # telemetry-adjacent: it must never die silently mid-fleet
                METRICS.count("elastic.step_errors")
                logger.warning(
                    "tfrecord.elastic serving step failed: %s", e
                )


class ServingReplicaSpawner:
    """The production serving ``spawn``: each call launches one
    ``python -m tpu_tfrecord.serving`` replica (synthetic model, seeded
    — the chaos/scale harness shape) with the given CLI args, BLOCKS on
    its ready line, and returns the replica's address — exactly what
    :class:`ServingScaler` appends to its member list. ``reap()`` is the
    shutdown safety net for replicas still alive (a drained replica
    exits on its own)."""

    def __init__(
        self,
        extra_args: tuple = (),
        env: Optional[Dict[str, str]] = None,
    ):
        self.extra_args = tuple(str(a) for a in extra_args)
        self.env = dict(env) if env is not None else None
        self.procs: List[Any] = []
        self._lock = threading.Lock()

    def __call__(self) -> str:
        import json as _json
        import subprocess
        import sys

        env = dict(self.env) if self.env is not None else dict(os.environ)
        pkg_parent = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        env["PYTHONPATH"] = (
            pkg_parent + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else pkg_parent
        )
        p = subprocess.Popen(
            [sys.executable, "-m", "tpu_tfrecord.serving", *self.extra_args],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env=env,
        )
        with self._lock:
            self.procs.append(p)
        line = p.stdout.readline()
        if not line:
            raise OSError("serving replica died before its ready line")
        return str(_json.loads(line)["addr"])

    def reap(self, timeout: float = 10.0) -> None:
        with self._lock:
            procs = list(self.procs)
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=timeout)
                except Exception:  # noqa: BLE001  # graftlint: swallow(best-effort shutdown reap; kill() fallback follows)
                    try:
                        p.kill()
                    except OSError:
                        pass
