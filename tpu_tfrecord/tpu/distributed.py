"""Multi-host coordination over jax.distributed.

The reference's distributed backend is Spark's: driver->executor broadcast of
the job conf + RDD aggregate tree-merge for schema inference (SURVEY.md §2
parallelism table, §5). The TPU-native equivalents:

- process coordination: ``jax.distributed.initialize`` (DCN); collectives on
  data ride ICI only inside jit-compiled computations.
- conf shipping: TFRecordOptions is a plain picklable value (options.py); no
  broadcast machinery is needed because every host derives identical state
  deterministically (same paths -> same sorted shard list -> same
  assignment).
- schema-inference merge: each host computes a partial type map over ITS
  shards (the seqOp of TensorFlowInferSchema.scala:40-43), then the JSON-coded
  partials are allgathered over the mesh and every host applies the same
  deterministic combOp merge — no host-0 special case, no extra broadcast.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

import jax
import numpy as np

from tpu_tfrecord.infer import TypeMap, merge_type_maps, type_map_to_schema
from tpu_tfrecord.schema import StructType, data_type_from_json


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize multi-host JAX if needed; safe no-op when single-process."""
    if num_processes in (None, 1) and coordinator_address is None:
        return
    # env-only check: probing jax.default_backend() would initialize the
    # ambient backend, which hangs forever on a dead device tunnel
    if os.environ.get("JAX_PLATFORMS", "").strip().lower().startswith("cpu"):
        # CPU fleets need an explicit cross-process collectives impl:
        # without it, a computation spanning processes dies with
        # "Multiprocess computations aren't implemented on the CPU
        # backend" the moment no process holds a whole replica (e.g. the
        # 2-process x 1-device dryrun). Must be set BEFORE the backend
        # client is created; harmless when already initialized.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # graftlint: swallow(older/newer jax without the knob: keep prior behavior)
            pass  # older/newer jax without the knob: keep prior behavior
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _encode_type_map(type_map: TypeMap) -> bytes:
    obj = {
        name: (None if dtype is None else dtype.to_json())
        for name, dtype in type_map.items()
    }
    return json.dumps(obj, sort_keys=True).encode("utf-8")


def _decode_type_map(data: bytes) -> TypeMap:
    obj = json.loads(data.decode("utf-8"))
    return {
        name: (None if t is None else data_type_from_json(t))
        for name, t in obj.items()
    }


def allgather_bytes(payload: bytes) -> List[bytes]:
    """Allgather a variable-length byte string across processes.

    Two phases over jax.experimental.multihost_utils.process_allgather:
    lengths first (so every host can size the padded buffer), then the padded
    payload bytes. Single-process: identity.
    """
    if jax.process_count() == 1:
        return [payload]
    from jax.experimental import multihost_utils

    lengths = multihost_utils.process_allgather(
        np.asarray([len(payload)], dtype=np.int32)
    ).reshape(-1)
    max_len = int(lengths.max())
    padded = np.zeros(max_len, dtype=np.uint8)
    padded[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    gathered = multihost_utils.process_allgather(padded)
    gathered = np.asarray(gathered).reshape(jax.process_count(), max_len)
    return [bytes(gathered[i, : int(lengths[i])].tobytes()) for i in range(len(lengths))]


class DistributedInferenceError(RuntimeError):
    """One or more hosts' local inference seqOp failed; raised on EVERY
    host after the allgather completes, naming the failed processes."""


def merge_schema_across_hosts(
    local_type_map: TypeMap, local_error: Optional[str] = None
) -> StructType:
    """Distributed schema inference: allgather per-host partial type maps and
    fold them with the same combOp on every host (deterministic order ->
    identical result everywhere). The TPU-native analog of the reference's
    RDD.aggregate combOp tree-merge (TensorFlowInferSchema.scala:40-43).

    ``local_error``: if this host's local fold failed, pass the error string
    INSTEAD of raising before the collective — a pre-collective raise on one
    host leaves every peer blocked in the allgather forever. The error rides
    the gather in the map's place and every host raises the same
    DistributedInferenceError after the collective completes (the analog of
    Spark failing the job when one aggregate task fails)."""
    payload = (
        b"E" + local_error.encode("utf-8", "replace")
        if local_error is not None
        else b"M" + _encode_type_map(local_type_map)
    )
    gathered = allgather_bytes(payload)
    errors = [
        (i, p[1:].decode("utf-8", "replace"))
        for i, p in enumerate(gathered)
        if p[:1] == b"E"
    ]
    if errors:
        detail = "; ".join(f"process {i}: {msg}" for i, msg in errors)
        raise DistributedInferenceError(
            f"schema inference failed on {len(errors)} process(es): {detail}"
        )
    partials = [_decode_type_map(p[1:]) for p in gathered]
    merged: TypeMap = {}
    for partial in partials:
        merged = merge_type_maps(merged, partial)
    return type_map_to_schema(merged)


def finalize_distributed_write(output_path: str) -> None:
    """Multi-host write commit: every host calls this after its own
    DatasetWriter job committed its shards (each host writes with
    ``task_id=jax.process_index()`` so part files never collide). All hosts
    barrier, then host 0 alone writes the dataset-level ``_SUCCESS`` marker —
    a reader seeing the marker is guaranteed every host's shards are in
    place (the analog of Spark's driver-side job commit)."""
    multi = jax.process_count() > 1
    if multi:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"tfr_write_commit:{output_path}")
    if jax.process_index() == 0:
        from tpu_tfrecord.io.paths import write_success_marker

        write_success_marker(output_path)
    if multi:
        # second barrier: when this returns on ANY host, the marker exists
        # (on host 0's filesystem) — the postcondition downstream gating
        # code relies on
        multihost_utils.sync_global_devices(f"tfr_write_done:{output_path}")


def barrier(name: str) -> None:
    """Cross-process barrier (no-op single-process). Used e.g. to publish a
    dataset written by one host before the others read it."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"tfr_barrier:{name}")


def adopt_shared_trace_context(role: str = "worker"):
    """Give every process in this multihost run ONE trace id (process 0's),
    adopted onto the process-global span recorder — so per-host Chrome
    traces, pulse lines, and telemetry spool snapshots all correlate under
    a single id and ``telemetry.merge_chrome_traces`` fuses them into one
    labeled timeline. Rides the same allgather as schema inference (each
    host contributes its local context; everyone deterministically adopts
    index 0's ids). Non-zero processes record process 0's root span as
    their parent; every process keeps its own span id/host/pid. Returns
    the adopted TraceContext. Single-process: just adopts the local
    context with ``role``."""
    import dataclasses

    from tpu_tfrecord import telemetry

    local = telemetry.current_context()
    gathered = allgather_bytes(
        json.dumps(local.to_json(), sort_keys=True).encode("utf-8")
    )
    root = telemetry.TraceContext.from_json(
        json.loads(gathered[0].decode("utf-8"))
    )
    ctx = dataclasses.replace(
        local,
        trace_id=root.trace_id,
        parent_span_id=(
            None if jax.process_index() == 0 else root.span_id
        ),
        role=role,
    )
    return telemetry.adopt(ctx)


def shared_service_address(addr: str) -> str:
    """Validate that every host of a multihost run points its consumers at
    the SAME data-service dispatcher before any bytes flow (rides the
    existing allgather). Two hosts talking to two dispatchers would each
    get self-consistent but differently-leased epochs — the classic
    silently-diverged-fleet failure this module's consistency checks
    exist for. Returns ``addr`` so call sites can inline it:
    ``options = {..., "service": shared_service_address(addr)}``."""
    assert_same_across_hosts(
        str(addr).encode("utf-8"), "data-service dispatcher address"
    )
    return str(addr)


def assert_same_across_hosts(value: bytes, what: str = "value") -> None:
    """Cheap cross-host consistency check (e.g. schema JSON, shard-list
    digest) — catches divergent host state before it corrupts a run."""
    gathered = allgather_bytes(value)
    if any(g != value for g in gathered):
        raise RuntimeError(f"{what} differs across hosts")
