"""Transfer bit-packing: shrink host->device bytes for bounded-int columns.

Hashed categorical features are bucket indices in ``[0, hash_buckets)`` —
for the common 2**20-bucket embedding table that is 20 significant bits
carried in a 32-bit lane: 37.5% of every transferred byte is zero padding.
On TPU the host->device link (PCIe, or a forwarded tunnel in dev setups) is
often the scarcest resource in an ingest pipeline, while on-device bit
twiddling is effectively free once fused into the consumer's jit program.

``pack_bits`` packs the columns of an int32 matrix into ``bits``-wide lanes
inside a narrower int32 matrix on the host (one vectorized numpy pass);
``unpack_bits`` is its exact inverse built from jax ops — shifts, masks and
a (C_out x C_in) gather — that XLA fuses into whatever consumes the batch.
Round-trip is bit-exact for any values < 2**bits.

The reference framework never needed this: its JVM rows stayed on the host
(SURVEY.md L2/L3). It exists here because a TPU-first ingest path budgets
bytes-per-example against link bandwidth, the same way BASELINE.md's
north-star metric does.
"""

from __future__ import annotations

import numpy as np

__all__ = ["packed_width", "pack_bits", "pack_mixed", "unpack_bits"]

_LANE = 32  # packing lane width: int32, the narrowest common transfer dtype


def packed_width(n_cols: int, bits: int) -> int:
    """Number of int32 output columns for ``n_cols`` values of ``bits`` each."""
    if not 1 <= bits <= _LANE:
        raise ValueError(f"bits must be in [1, {_LANE}], got {bits}")
    return (n_cols * bits + _LANE - 1) // _LANE


def pack_bits(arr: np.ndarray, bits: int) -> np.ndarray:
    """Pack ``arr[:, j] < 2**bits`` (int32/int64, non-negative) into a dense
    [B, packed_width] int32 matrix, little-endian within and across lanes:
    value j occupies global bit positions [j*bits, (j+1)*bits).

    Values are masked to ``bits`` (callers hash/bucket first, which already
    guarantees the range); negatives are rejected — two's-complement lanes
    would silently corrupt neighbours. Round-trip restores the low ``bits``
    bit pattern; since the unpacked dtype is int32, values in
    ``[2**31, 2**32)`` (only possible at bits=32) come back as their int32
    reinterpretation.
    """
    if arr.ndim != 2:
        raise ValueError(f"pack_bits expects [B, C], got shape {arr.shape}")
    b, c = arr.shape
    w = packed_width(c, bits)
    if np.issubdtype(arr.dtype, np.signedinteger) and arr.size and arr.min() < 0:
        raise ValueError("pack_bits requires non-negative values")
    if bits == _LANE:
        return (
            (arr.astype(np.uint64) & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
        )
    vals = arr.astype(np.uint64) & ((1 << bits) - 1)
    out = np.zeros((b, w), dtype=np.uint64)  # u64 scratch absorbs lane spill
    starts = np.arange(c, dtype=np.int64) * bits
    lanes = starts // _LANE
    offs = starts % _LANE
    for j in range(c):
        lane, off = int(lanes[j]), int(offs[j])
        out[:, lane] |= vals[:, j] << off
        spill = off + bits - _LANE
        if spill > 0:
            out[:, lane + 1] |= vals[:, j] >> (bits - spill)
    # low 32 bits of each u64 lane are the packed stream
    return (out & 0xFFFFFFFF).astype(np.uint32).view(np.int32)


def pack_mixed(arr: np.ndarray, keep: int, bits: int) -> np.ndarray:
    """Mixed-width wire matrix: the first ``keep`` int32 lanes of each row
    pass through verbatim, the remaining columns bit-pack to ``bits`` —
    the one-call form of ``concatenate([arr[:, :keep], pack_bits(arr[:,
    keep:], bits)])`` with a native single-pass kernel on the hot path
    (csrc tfr_pack_mixed; numpy fallback is bit-identical, pinned in
    tests/test_bitpack.py). The consumer unpacks the tail with
    ``unpack_bits(wire[:, keep:], C - keep, bits)``.
    """
    if arr.ndim != 2:
        raise ValueError(f"pack_mixed expects [B, C], got shape {arr.shape}")
    if not 0 <= keep <= arr.shape[1]:
        raise ValueError(f"keep={keep} out of range for {arr.shape[1]} columns")
    packed_width(1, bits)  # validate bits BEFORE dispatching to the kernel
    if arr.dtype == np.int32:
        # hot path (decode emits int32 group matrices): single native pass,
        # sign validation rides the kernel loop — no extra numpy scan
        try:
            from tpu_tfrecord import _native

            if _native.available():
                out = _native.pack_mixed(arr, keep, bits)
                if out is not None:
                    return out
        except ImportError:
            pass
    # pack_bits performs the negative-value rejection for the tail
    return np.concatenate(
        [np.ascontiguousarray(arr[:, :keep]).astype(np.int32),
         pack_bits(arr[:, keep:], bits)],
        axis=1,
    )


def unpack_bits(packed, n_cols: int, bits: int):
    """Inverse of :func:`pack_bits` as jax ops: [B, packed_width] int32 ->
    [B, n_cols] int32. Call inside the consumer's jit — XLA fuses the
    gather/shift/mask into the surrounding program, so the unpack costs no
    extra HBM round-trip.
    """
    import jax.numpy as jnp

    if bits == _LANE:
        return packed
    u = packed.astype(jnp.uint32)
    starts = np.arange(n_cols, dtype=np.int64) * bits
    lanes = (starts // _LANE).astype(np.int32)
    offs = (starts % _LANE).astype(np.int32)
    spill = offs + bits - _LANE  # >0 where a value straddles two lanes
    lo = u[:, lanes] >> jnp.asarray(offs, dtype=jnp.uint32)[None, :]
    # high part: next lane's low bits, shifted up; masked off when no spill
    next_lane = np.minimum(lanes + 1, packed.shape[1] - 1).astype(np.int32)
    hi_shift = np.where(spill > 0, bits - spill, 0).astype(np.int64)
    hi = u[:, next_lane] << jnp.asarray(hi_shift, dtype=jnp.uint32)[None, :]
    hi = jnp.where(jnp.asarray(spill > 0)[None, :], hi, jnp.zeros_like(hi))
    mask = jnp.uint32((1 << bits) - 1)
    return ((lo | hi) & mask).astype(jnp.int32)
