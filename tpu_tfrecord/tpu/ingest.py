"""Columnar host batches -> sharded jax.Array pytrees on a mesh.

The "aha slice" of SURVEY.md §7.6: a schema maps to a pytree of
jax.ShapeDtypeStruct; each host turns its ColumnarBatch into dense numpy
arrays (ragged columns padded/bucketed, string columns hashed or skipped);
`jax.make_array_from_process_local_data` assembles the global array whose
batch dim is sharded over the mesh's 'data' axis. A double-buffered
DeviceIterator overlaps host decode with device compute so the input pipeline
stays off the critical path (the >=95% duty-cycle target, BASELINE.md).
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Iterator, List, Optional, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_tfrecord import wire
from tpu_tfrecord.columnar import Column, ColumnarBatch, pad_ragged, pad_ragged2
from tpu_tfrecord.metrics import METRICS, timed
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DataType,
    StringType,
    StructType,
    numpy_dtype,
)

# ---------------------------------------------------------------------------
# Specs
# ---------------------------------------------------------------------------


def _is_bytes_like(dt: DataType) -> bool:
    if isinstance(dt, (StringType, BinaryType)):
        return True
    if isinstance(dt, ArrayType):
        return _is_bytes_like(dt.element_type)
    return False


def _validate_cast(schema: StructType, cast: Dict[str, np.dtype]) -> None:
    """Every cast key must name a numeric schema column — a typo'd name
    would otherwise silently skip the cast (mirrors validate_hash_buckets'
    eager unknown-column error)."""
    castable = {
        f.name for f in schema if not _is_bytes_like(f.data_type)
    }
    for name in cast:
        if name not in castable:
            raise ValueError(
                f"cast: no castable data column named {name!r} "
                f"(numeric columns: {sorted(castable)})"
            )


def batch_spec(
    schema: StructType,
    batch_size: int,
    pad_to: Optional[Dict[str, Union[int, tuple]]] = None,
    hash_buckets: Optional[Dict[str, int]] = None,
    include_lengths: bool = True,
    cast: Optional[Dict[str, np.dtype]] = None,
) -> Dict[str, jax.ShapeDtypeStruct]:
    """Schema -> pytree of ShapeDtypeStruct for one global batch.

    - numeric scalar column            -> (B,) of its numpy dtype
    - numeric array column             -> (B, L) + '<name>_len' (B,) int32
    - array-of-array column            -> (B, Lo, Li) + '<name>_len' (B,)
                                          + '<name>_inner_len' (B, Lo)
    - string/binary column             -> (B,) int32 iff hashed via
                                          ``hash_buckets[name]``, else omitted
                                          (int32: embedding row indices —
                                          half the transfer bytes of int64)
    ``pad_to`` must give L (or (Lo, Li)) for every ragged column — static
    shapes are what let XLA tile the downstream compute onto the MXU.
    ``cast`` overrides a column's device dtype (e.g. ``{"frames":
    ml_dtypes.bfloat16}`` — halves link bytes; the fused native pad+cast
    makes it free on the host side).
    """
    pad_to = pad_to or {}
    hash_buckets = hash_buckets or {}
    cast = cast or {}
    _validate_cast(schema, cast)
    spec: Dict[str, jax.ShapeDtypeStruct] = {}

    def col_dtype(name: str, dt: DataType):
        return np.dtype(cast[name]) if name in cast else numpy_dtype(dt)

    for f in schema:
        dt = f.data_type
        if _is_bytes_like(dt):
            if f.name in hash_buckets:
                if isinstance(dt, ArrayType):  # multi-hot: [B, K] + lengths
                    k = pad_to[f.name]
                    spec[f.name] = jax.ShapeDtypeStruct((batch_size, k), np.int32)
                    if include_lengths:
                        spec[f.name + "_len"] = jax.ShapeDtypeStruct(
                            (batch_size,), np.int32
                        )
                else:
                    spec[f.name] = jax.ShapeDtypeStruct((batch_size,), np.int32)
            continue
        if isinstance(dt, ArrayType):
            if isinstance(dt.element_type, ArrayType):
                lo, li = pad_to[f.name]
                spec[f.name] = jax.ShapeDtypeStruct(
                    (batch_size, lo, li), col_dtype(f.name, dt)
                )
                if include_lengths:
                    spec[f.name + "_len"] = jax.ShapeDtypeStruct((batch_size,), np.int32)
                    spec[f.name + "_inner_len"] = jax.ShapeDtypeStruct(
                        (batch_size, lo), np.int32
                    )
            else:
                length = pad_to[f.name]
                spec[f.name] = jax.ShapeDtypeStruct(
                    (batch_size, length), col_dtype(f.name, dt)
                )
                if include_lengths:
                    spec[f.name + "_len"] = jax.ShapeDtypeStruct((batch_size,), np.int32)
        else:
            spec[f.name] = jax.ShapeDtypeStruct((batch_size,), col_dtype(f.name, dt))
    return spec


# ---------------------------------------------------------------------------
# Host-side densification
# ---------------------------------------------------------------------------


def hash_bytes_column(col_or_blobs, num_buckets: int) -> np.ndarray:
    """Deterministic CRC32C-based hashing of byte strings into buckets —
    the host-side categorical-feature path (strings never go to the TPU).
    Accepts a bytes-like Column (flat blob path, hashed in one native call)
    or a plain list of bytes."""
    if isinstance(col_or_blobs, Column):
        col = col_or_blobs
        try:
            from tpu_tfrecord import _native

            if _native.available():
                return _native.hash_blob(
                    col.blob, col.blob_offsets, num_buckets
                ).astype(np.int32)
        except Exception:  # graftlint: swallow(native hash unavailable: python path below is the oracle)
            pass
        blobs = col.blobs
    else:
        blobs = col_or_blobs
    out = np.empty(len(blobs), dtype=np.int32)
    c32 = wire.crc32c
    for i, b in enumerate(blobs):
        out[i] = c32(b) % num_buckets
    return out


def _pad_ragged_cast(col: Column, max_len: int, out_dtype) -> tuple:
    """One-level pad with optional dtype cast, native-fused when possible."""
    from tpu_tfrecord import _native

    if _native.available():
        res = _native.pad_ragged_dense(col.values, col.offsets, max_len, out_dtype)
        if res is not None:
            return res
    dense, lengths = pad_ragged(col.values, col.offsets, max_len)
    if out_dtype is not None and dense.dtype != np.dtype(out_dtype):
        dense = dense.astype(out_dtype)
    return dense, lengths


def _pad_ragged2_cast(col: Column, lo: int, li: int, out_dtype) -> tuple:
    """Two-level pad with optional dtype cast, native-fused when possible."""
    from tpu_tfrecord import _native

    if _native.available():
        res = _native.pad_ragged2_dense(
            col.values, col.inner_offsets, col.offsets, lo, li, out_dtype
        )
        if res is not None:
            return res
    dense, outer_len, inner_len = pad_ragged2(
        col.values, col.inner_offsets, col.offsets, lo, li
    )
    if out_dtype is not None and dense.dtype != np.dtype(out_dtype):
        dense = dense.astype(out_dtype)
    return dense, outer_len, inner_len


def host_batch_from_columnar(
    batch: ColumnarBatch,
    schema: StructType,
    pad_to: Optional[Dict[str, Union[int, tuple]]] = None,
    hash_buckets: Optional[Dict[str, int]] = None,
    include_lengths: bool = True,
    pack: Optional[Dict[str, List[str]]] = None,
    cast: Optional[Dict[str, np.dtype]] = None,
) -> Dict[str, np.ndarray]:
    """ColumnarBatch -> dict of dense numpy arrays matching batch_spec.

    ``pack`` groups same-dtype scalar columns into one [B, K] array
    (``{"dense": ["I1", ...], "cat": ["C1", ...]}``) — fewer, larger
    device transfers (one dispatch per group instead of per column) and the
    natural layout for MXU-bound consumers like the DLRM model.

    ``cast`` maps column name -> output dtype (e.g. bfloat16 for float
    frames). For ragged columns the pad and the cast run fused in the native
    kernel — the f32->bf16 conversion never materializes an f32 dense batch.
    """
    pad_to = pad_to or {}
    hash_buckets = hash_buckets or {}
    cast = cast or {}
    _validate_cast(schema, cast)
    if cast and pack:
        # A pack group is ONE matrix with one dtype — a per-member cast
        # would be silently skipped when the group was materialized by the
        # native decoder, defeating _validate_cast's loud-failure contract.
        for group, names in pack.items():
            overlap = sorted(set(cast) & set(names))
            if overlap:
                raise ValueError(
                    f"cast: columns {overlap} are members of pack group "
                    f"{group!r}; casting packed members is not supported"
                )
    out: Dict[str, np.ndarray] = {}
    # Groups already materialized by the native decoder (pack pushed down):
    # take their matrices directly and skip the member fields.
    packed_members = set()
    if pack:
        for group, names in pack.items():
            if group in batch:
                out[group] = batch[group].values
                packed_members.update(names)
    for f in schema:
        if f.name in packed_members:
            continue
        col = batch[f.name]
        dt = f.data_type
        if _is_bytes_like(dt):
            if f.name in hash_buckets:
                if col.is_ragged:
                    # multi-hot categorical: ragged hashed indices pad to
                    # [B, K] + lengths (consumers mask/pool over K)
                    if f.name not in pad_to:
                        raise ValueError(
                            f"multi-hot column {f.name!r} requires pad_to[{f.name!r}]"
                        )
                    if col.values is not None:
                        # fused: already int32 indices — bucket counts must
                        # agree, same contract as the scalar path
                        if (
                            col.hash_buckets is not None
                            and col.hash_buckets != hash_buckets[f.name]
                        ):
                            raise ValueError(
                                f"{f.name}: decoded with hash_buckets="
                                f"{col.hash_buckets} but host batch requests "
                                f"{hash_buckets[f.name]}"
                            )
                        vals = col.values
                    else:
                        vals = hash_bytes_column(col, hash_buckets[f.name])
                    dense, lengths = pad_ragged(vals, col.offsets, pad_to[f.name])
                    out[f.name] = dense
                    if include_lengths:
                        out[f.name + "_len"] = lengths
                    continue
                if col.values is not None:
                    # already hashed during decode (fused native path)
                    if (
                        col.hash_buckets is not None
                        and col.hash_buckets != hash_buckets[f.name]
                    ):
                        raise ValueError(
                            f"{f.name}: decoded with hash_buckets="
                            f"{col.hash_buckets} but host batch requests "
                            f"{hash_buckets[f.name]}"
                        )
                    out[f.name] = col.values
                else:
                    out[f.name] = hash_bytes_column(col, hash_buckets[f.name])
            continue
        if isinstance(dt, ArrayType):
            if isinstance(dt.element_type, ArrayType):
                lo, li = pad_to[f.name]
                dense, outer_len, inner_len = _pad_ragged2_cast(
                    col, lo, li, cast.get(f.name)
                )
                out[f.name] = dense
                if include_lengths:
                    out[f.name + "_len"] = outer_len
                    out[f.name + "_inner_len"] = inner_len
            else:
                if f.name not in pad_to:
                    # Padding to the per-batch max would make shapes vary
                    # batch-to-batch (jit recompiles; per-host shapes diverge
                    # multi-host) — require an explicit static length, same
                    # as batch_spec.
                    raise ValueError(
                        f"ragged column {f.name!r} requires pad_to[{f.name!r}]"
                    )
                dense, lengths = _pad_ragged_cast(
                    col, pad_to[f.name], cast.get(f.name)
                )
                out[f.name] = dense
                if include_lengths:
                    out[f.name + "_len"] = lengths
        else:
            vals = col.values
            if f.name in cast and vals.dtype != np.dtype(cast[f.name]):
                vals = vals.astype(cast[f.name])
            out[f.name] = vals
    if pack:
        for group, names in pack.items():
            if group in out:
                continue  # decoded as a matrix already
            cols = [out.pop(n) for n in names]
            out[group] = np.stack(cols, axis=1)
    return out


# ---------------------------------------------------------------------------
# Global array assembly
# ---------------------------------------------------------------------------


def data_shardings(
    host_batch: Dict[str, np.ndarray], mesh: Mesh, axis: str = "data"
) -> Dict[str, NamedSharding]:
    """Batch-dim-on-``axis`` sharding for every array in a host batch.
    Precompute once per batch structure — sharding construction is pure
    Python overhead on the per-batch hot path."""
    return {
        name: NamedSharding(mesh, P(axis, *([None] * (arr.ndim - 1))))
        for name, arr in host_batch.items()
    }


def make_global_batch(
    host_batch: Dict[str, np.ndarray],
    mesh: Mesh,
    axis: str = "data",
    shardings: Optional[Dict[str, NamedSharding]] = None,
) -> Dict[str, jax.Array]:
    """Per-host numpy batch -> pytree of GLOBAL jax.Arrays sharded on
    ``axis``. Each host contributes its local rows; across P processes the
    global batch dim is P * local_batch (jax.make_array_from_process_local_data
    — the BASELINE.json north-star assembly path)."""
    from tpu_tfrecord.tracing import trace

    single_process = jax.process_count() == 1
    with timed("h2d", METRICS) as t, trace("tfr:h2d"):
        if shardings is None:
            shardings = data_shardings(host_batch, mesh, axis)
        if single_process:
            # local == global: ONE sharded device_put over the whole pytree —
            # a single dispatch instead of one per array
            out = jax.device_put(host_batch, shardings)
        else:
            out = {
                name: jax.make_array_from_process_local_data(shardings[name], arr)
                for name, arr in host_batch.items()
            }
        for arr in host_batch.values():
            t.bytes += arr.nbytes
        t.records += next(iter(host_batch.values())).shape[0] if host_batch else 0
    return out


class TokenPacker:
    """Ragged token documents -> packed causal-LM batches [B, L+1] int32.

    Three packing modes (the ``packing`` argument):

    - ``"slice"`` (default): documents are concatenated with an EOS
      separator and sliced into non-overlapping windows of L+1 tokens
      (the consumer reads ``row[:-1]`` and scores against ``row[1:]``),
      so every batch is fully dense — no padding, no masks, maximal MXU
      utilization — the standard packed-LM feed. The window boundary
      drops no tokens (the residual tail carries into the next batch)
      but DOES split documents across rows, and rows mix documents with
      no boundary signal: attention leaks across documents.
    - ``"first_fit"`` / ``"best_fit"``: bin packing. Each document (+
      its EOS; documents longer than L+1 are pre-split into L+1-sized
      chunks, each chunk its own segment) is placed whole into one of up
      to B open row-bins of capacity L+1 — first_fit takes the
      lowest-indexed bin it fits, best_fit the fitting bin with the
      LEAST remaining room (ties to the lowest index). When a chunk fits
      no bin and all B are open, the batch closes: rows pad to L+1 with
      EOS and ``pop()`` returns ``{"tokens": [B, L+1], "segment_ids":
      [B, L+1]}`` — ids number each row's documents 1..k in placement
      order, pad positions are 0 — the block-diagonal mask feed for
      `models.attention` ``segments``. Density (non-pad fraction,
      ``density()``) is < 1 but no document ever crosses a row.

    The carry (residual tokens / open bins + any already-packed-but-
    unpopped rows) is the ONLY state, exposed via ``state()``/
    ``restore()`` as a small JSON payload, so a training job checkpoints
    it NEXT TO the dataset's `IteratorState` and a kill -9/resume
    replays the packed stream byte-identically (pinned by
    examples/train_lm.py's harness test).
    """

    _MODES = ("slice", "first_fit", "best_fit")

    def __init__(
        self, batch_size: int, seq_len: int, eos_id: int = 0,
        packing: str = "slice",
    ):
        if batch_size < 1 or seq_len < 1:
            raise ValueError(
                f"batch_size and seq_len must be >= 1, got "
                f"({batch_size}, {seq_len})"
            )
        if packing not in self._MODES:
            raise ValueError(
                f"packing must be one of {self._MODES}, got {packing!r}"
            )
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.eos_id = int(eos_id)
        self.packing = packing
        self._buf: List[np.ndarray] = []   # chunks, flattened lazily
        self._buf_len = 0
        # bin modes: open row-bins, each a list of document chunks
        self._bins: List[List[np.ndarray]] = []
        self._pending: List[Any] = []  # ready [B, L+1] batches / dicts
        # density accounting (bin modes; slice mode is 1.0 by construction)
        self._emitted_tokens = 0
        self._emitted_nonpad = 0

    def feed_docs(self, docs: Iterable[np.ndarray]) -> None:
        """Append documents (1-D int arrays) to the stream, EOS after each."""
        if self.packing != "slice":
            self._feed_docs_bins(docs)
            return
        eos = np.asarray([self.eos_id], np.int32)
        for doc in docs:
            arr = np.asarray(doc).astype(np.int32, copy=False).reshape(-1)
            self._buf.append(arr)
            self._buf.append(eos)
            self._buf_len += arr.size + 1
        self._drain()

    def _feed_docs_bins(self, docs: Iterable[np.ndarray]) -> None:
        cap = self.seq_len + 1
        eos = np.asarray([self.eos_id], np.int32)
        for doc in docs:
            arr = np.asarray(doc).astype(np.int32, copy=False).reshape(-1)
            arr = np.concatenate([arr, eos])
            # long documents pre-split into cap-sized chunks; each chunk
            # is its own attention segment (they cannot share a row and
            # attend to each other anyway)
            for at in range(0, arr.size, cap):
                self._place_chunk(arr[at : at + cap])

    def _place_chunk(self, chunk: np.ndarray) -> None:
        cap = self.seq_len + 1
        fit = -1
        if self.packing == "best_fit":
            best_room = cap + 1
            for i, b in enumerate(self._bins):
                room = cap - sum(c.size for c in b)
                if chunk.size <= room < best_room:
                    fit, best_room = i, room
        else:  # first_fit — the greedy binning baseline
            for i, b in enumerate(self._bins):
                if chunk.size <= cap - sum(c.size for c in b):
                    fit = i
                    break
        if fit >= 0:
            self._bins[fit].append(chunk)
            return
        if len(self._bins) == self.batch_size:
            self._close_bins()
        self._bins.append([chunk])

    def _close_bins(self) -> None:
        """Flush the B open bins into one pending {tokens, segment_ids}
        batch: rows pad to L+1 with EOS, pad segment id 0."""
        cap = self.seq_len + 1
        toks = np.full((self.batch_size, cap), self.eos_id, np.int32)
        segs = np.zeros((self.batch_size, cap), np.int32)
        nonpad = 0
        for r, b in enumerate(self._bins):
            at = 0
            for s, chunk in enumerate(b):
                toks[r, at : at + chunk.size] = chunk
                segs[r, at : at + chunk.size] = s + 1
                at += chunk.size
            nonpad += at
        self._bins = []
        self._pending.append({"tokens": toks, "segment_ids": segs})
        self._emitted_tokens += self.batch_size * cap
        self._emitted_nonpad += nonpad
        METRICS.gauge("pack.density", round(self.density(), 4))

    def density(self) -> float:
        """Fraction of emitted batch tokens that are real document tokens
        (1.0 until a bin-mode batch closes; slice mode is 1.0 always —
        the window slicing leaves no padding)."""
        if not self._emitted_tokens:
            return 1.0
        return self._emitted_nonpad / self._emitted_tokens

    def feed_column(self, col) -> None:
        """Feed a ragged int Column straight from a ColumnarBatch: the
        flat values/offsets ARE the document boundaries."""
        values = np.asarray(col.values)
        offsets = np.asarray(col.offsets)
        self.feed_docs(
            values[offsets[i] : offsets[i + 1]]
            for i in range(len(offsets) - 1)
        )

    def _drain(self) -> None:
        need = self.batch_size * (self.seq_len + 1)
        if self._buf_len < need:
            return
        flat = np.concatenate(self._buf) if len(self._buf) > 1 else self._buf[0]
        n_batches = flat.size // need
        take = n_batches * need
        for i in range(n_batches):
            self._pending.append(
                flat[i * need : (i + 1) * need]
                .reshape(self.batch_size, self.seq_len + 1)
                .copy()
            )
        rest = flat[take:]
        self._buf = [rest] if rest.size else []
        self._buf_len = int(rest.size)

    def pop(self):
        """Next ready batch, or None when more docs are needed: a
        [B, L+1] int32 array in slice mode, a ``{"tokens": [B, L+1],
        "segment_ids": [B, L+1]}`` dict in the bin modes."""
        return self._pending.pop(0) if self._pending else None

    def state(self) -> dict:
        """JSON-able carry: checkpoint it WITH the dataset IteratorState
        taken at the same point so resume replays byte-identically. Slice
        mode keeps its historical {residual, pending} shape (old
        checkpoints restore unchanged); bin modes carry the open bins
        (per-row chunk lists), the pending {tokens, segment_ids} dicts,
        and the density accumulators."""
        if self.packing == "slice":
            flat = (
                np.concatenate(self._buf).tolist() if self._buf else []
            )
            return {
                "residual": flat,
                "pending": [b.tolist() for b in self._pending],
            }
        return {
            "bins": [[c.tolist() for c in b] for b in self._bins],
            "pending": [
                {
                    "tokens": d["tokens"].tolist(),
                    "segment_ids": d["segment_ids"].tolist(),
                }
                for d in self._pending
            ],
            "emitted_tokens": self._emitted_tokens,
            "emitted_nonpad": self._emitted_nonpad,
        }

    def restore(self, state: dict) -> None:
        if self.packing == "slice":
            residual = np.asarray(state.get("residual", []), np.int32)
            self._buf = [residual] if residual.size else []
            self._buf_len = int(residual.size)
            self._pending = [
                np.asarray(b, np.int32) for b in state.get("pending", [])
            ]
            return
        self._bins = [
            [np.asarray(c, np.int32) for c in b]
            for b in state.get("bins", [])
        ]
        self._pending = [
            {
                "tokens": np.asarray(d["tokens"], np.int32),
                "segment_ids": np.asarray(d["segment_ids"], np.int32),
            }
            for d in state.get("pending", [])
        ]
        self._emitted_tokens = int(state.get("emitted_tokens", 0))
        self._emitted_nonpad = int(state.get("emitted_nonpad", 0))


class HostPrefetcher:
    """Run a host-batch iterator in a background thread behind a bounded
    queue.

    The dataset's decode already overlaps (its own producer thread, GIL
    released in the native codec), but the numpy tail of batch production —
    pad/pack/hash in ``host_batch_from_columnar`` — otherwise runs inline in
    the consumer thread, inside the device's input-wait. Wrapping the host
    batch generator here moves that work off the critical path too, which is
    what keeps the duty cycle >=95% when batch assembly is non-trivial
    (ragged padding, many columns). Iterate it, or use as a context manager;
    ``close()`` unblocks and joins the worker."""

    _DONE = object()

    def __init__(self, host_batches: Iterable[Dict[str, np.ndarray]], depth: int = 2):
        import queue
        import threading

        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._empty = queue.Empty  # shutdown-safe binding (module may be gone)
        self._finished: Optional[object] = None

        def _produce():
            try:
                for hb in host_batches:
                    while not self._stop.is_set():
                        try:
                            self._queue.put(hb, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if self._stop.is_set():
                        return
                self._queue.put(self._DONE)
            except BaseException as e:  # noqa: BLE001 — repropagated in consumer  # graftlint: swallow(exception forwarded to the consumer queue, repropagated)
                self._queue.put(e)

        self._thread = threading.Thread(target=_produce, daemon=True)
        self._thread.start()

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        # The sentinel/exception arrives on the queue exactly once — cache
        # it so a second next() after exhaustion re-raises instead of
        # blocking forever on an empty queue with a dead producer.
        if self._finished is not None:
            if self._finished is self._DONE:
                raise StopIteration
            raise self._finished
        item = self._queue.get()
        if item is self._DONE:
            self._finished = item
            raise StopIteration
        if isinstance(item, BaseException):
            self._finished = item
            raise item
        return item

    def close(self) -> None:
        self._stop.set()
        if self._finished is None:
            # Post-close iteration must raise StopIteration, not park on a
            # queue whose producer is gone.
            self._finished = self._DONE
        try:
            while True:
                self._queue.get_nowait()
        except self._empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "HostPrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class DeviceIterator:
    """Double-buffered device feeder: host batches -> sharded global batches.

    Starts the transfer of batch N+1 while the consumer computes on batch N
    (dispatch is async in JAX, so `make_array_from_process_local_data` returns
    as soon as the transfer is enqueued). This is the device_put overlap the
    reference never needed (the JVM never touched an accelerator) but a TPU
    input pipeline lives or dies by (SURVEY.md §7 hard part e).

    ``transfer_thread=True`` moves the transfer into a dedicated worker that
    BLOCKS each copy to completion behind a bounded queue of device-resident
    batches. On platforms where the host-to-device copy is synchronous at
    dispatch (a dispatched transfer makes no progress until some thread
    blocks on it — true of network-tunneled devices, unlike PCIe PJRT's
    async H2D engine), dispatch-ahead alone overlaps nothing; the worker
    thread restores the overlap because it does its blocking while the
    consumer thread sits inside the device step. Use ``close()`` (or a
    ``with`` block) to release the worker."""

    def __init__(
        self,
        host_batches: Iterable[Dict[str, np.ndarray]],
        mesh: Mesh,
        axis: str = "data",
        transfer_thread: bool = False,
        depth: int = 2,
    ):
        self._it = iter(host_batches)
        self._mesh = mesh
        self._axis = axis
        self._pending: Optional[Dict[str, jax.Array]] = None
        self._shardings: Optional[Dict[str, NamedSharding]] = None
        self._sharding_key: Optional[Dict[str, int]] = None
        self._pf: Optional[HostPrefetcher] = None
        #: Cumulative host-side seconds spent transferring batches to the
        #: device (dispatch, plus the block-to-completion in threaded
        #: mode). The training harness (examples/_harness.StepPhases)
        #: snapshots this around each ``next()`` to split the step's wait
        #: window into ``train.data_wait`` vs ``train.h2d`` — without it,
        #: every inline H2D copy would masquerade as input-pipeline wait
        #: and the training verdict would blame the wrong layer.
        self.transfer_seconds = 0.0
        if transfer_thread:
            # Delegate the thread/queue/sentinel protocol to HostPrefetcher
            # (it is item-type-agnostic); the generator below is what runs
            # on its worker: transfer + block each copy to completion, so
            # the consumer pops already-device-resident batches.
            def _transferred():
                for host in self._it:
                    t0 = time.perf_counter()
                    gb = self._transfer(host, _timed=False)
                    jax.block_until_ready(gb)
                    self.transfer_seconds += time.perf_counter() - t0
                    yield gb

            self._pf = HostPrefetcher(_transferred(), depth=depth)

    def _transfer(
        self, host: Dict[str, np.ndarray], _timed: bool = True
    ) -> Dict[str, jax.Array]:
        # Cache key includes each array's ndim: a same-named array changing
        # rank between batches must rebuild its NamedSharding (a stale
        # PartitionSpec of the wrong rank would shard incorrectly or fail).
        t0 = time.perf_counter()
        shape_key = {name: arr.ndim for name, arr in host.items()}
        if self._shardings is None or self._sharding_key != shape_key:
            self._shardings = data_shardings(host, self._mesh, self._axis)
            self._sharding_key = shape_key
        out = make_global_batch(host, self._mesh, self._axis, self._shardings)
        if _timed:  # threaded mode times transfer + block in one window
            self.transfer_seconds += time.perf_counter() - t0
        return out

    def __iter__(self) -> Iterator[Dict[str, jax.Array]]:
        return self

    def __next__(self) -> Dict[str, jax.Array]:
        if self._pf is not None:
            return next(self._pf)
        if self._pending is None:
            host = next(self._it)  # raises StopIteration at end
            self._pending = self._transfer(host)
        current = self._pending
        self._pending = None
        try:
            nxt = next(self._it)
        except StopIteration:
            return current
        self._pending = self._transfer(nxt)
        return current

    def close(self) -> None:
        """Release the transfer worker (no-op without ``transfer_thread``)."""
        if self._pf is not None:
            self._pf.close()

    def __enter__(self) -> "DeviceIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
