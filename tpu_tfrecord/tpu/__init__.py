"""TPU ingestion layer: datasets -> sharded jax.Array on a device mesh.

This is where the reference's data-parallel story (one Spark task per file,
SURVEY.md §2 parallelism table) becomes a TPU pod's: shards are assigned
per host, each host decodes its shards into columnar host batches, and
`jax.make_array_from_process_local_data` assembles global arrays sharded over
the mesh's 'data' axis. Ragged SequenceExample columns pad/bucket into dense
[batch, max_len] device arrays.
"""

from tpu_tfrecord.tpu.mesh import (
    assign_shards,
    create_mesh,
    data_sharding,
    local_batch_size,
)
from tpu_tfrecord.tpu.bitpack import pack_bits, pack_mixed, packed_width, unpack_bits
from tpu_tfrecord.tpu.ingest import (
    DeviceIterator,
    HostPrefetcher,
    TokenPacker,
    batch_spec,
    data_shardings,
    hash_bytes_column,
    host_batch_from_columnar,
    make_global_batch,
)

__all__ = [
    "create_mesh",
    "data_sharding",
    "assign_shards",
    "local_batch_size",
    "batch_spec",
    "data_shardings",
    "host_batch_from_columnar",
    "make_global_batch",
    "hash_bytes_column",
    "DeviceIterator",
    "HostPrefetcher",
    "TokenPacker",
    "pack_bits",
    "pack_mixed",
    "packed_width",
    "unpack_bits",
]
