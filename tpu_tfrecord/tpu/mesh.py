"""Device mesh construction and shard assignment.

The data-parallel axis here is the TPU-native re-design of the reference's
parallelism model (one Spark task per file; executor assignment by Spark's
scheduler — SURVEY.md §2 parallelism table): shards are assigned to HOSTS
deterministically, hosts feed their local devices, and the mesh's 'data' axis
carries the global batch. A 'model' axis is supported so consumers can lay
tensor-parallel computation over the same mesh without re-ingesting.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_tfrecord.io.paths import Shard


def create_mesh(
    axes: Optional[Dict[str, int]] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Create a Mesh from named axis sizes; one size may be -1 (inferred).

    Default: all devices on a single 'data' axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = dict(axes or {"data": n})
    unknown = [k for k, v in axes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError("at most one axis size may be -1")
    known = math.prod(v for v in axes.values() if v != -1)
    if unknown:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        axes[unknown[0]] = n // known
    if math.prod(axes.values()) != n:
        raise ValueError(f"mesh {axes} does not cover {n} devices")
    dev_array = np.asarray(devices).reshape(tuple(axes.values()))
    return Mesh(dev_array, tuple(axes.keys()))


def data_sharding(mesh: Mesh, axis: str = "data", ndim: int = 1) -> NamedSharding:
    """NamedSharding placing dim 0 on the data axis, rest replicated."""
    spec = [axis] + [None] * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def local_batch_size(global_batch: int, mesh: Mesh, axis: str = "data") -> int:
    """Per-process batch size for a global batch sharded on ``axis``."""
    axis_size = mesh.shape[axis]
    if global_batch % axis_size:
        raise ValueError(f"global batch {global_batch} not divisible by {axis_size}")
    pc = jax.process_count()
    if global_batch % pc:
        raise ValueError(
            f"global batch {global_batch} not divisible by process count {pc}"
        )
    return global_batch // pc


def assign_shards(
    shards: Sequence[Shard],
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> List[Shard]:
    """Deterministic interleaved per-host shard assignment.

    Every host computes the same global order (discover_shards sorts), then
    takes shards ``i`` with ``i % process_count == process_index`` — the
    analog of Spark's task placement, but static and reproducible so
    checkpoint/resume and multi-host runs agree without coordination.
    """
    from tpu_tfrecord.io.paths import interleave

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    return interleave(shards, pi, pc)
