"""Repo tooling (``python -m tools.graftlint``). Not shipped in the wheel
(pyproject packages.find includes only ``tpu_tfrecord*``)."""
