"""Measured multi-worker decode scaling, recorded as a JSON artifact.

The 1-core TPU bench box can never evidence the num_workers machinery's
actual parallel speedup (VERDICT r4 item 8) — the scaling test that runs on
multi-core CI is pass/fail only. This tool produces the tracked NUMBER: it
generates a Criteo-shaped dataset, measures sustained decode throughput at
num_workers = 1 and N (default: min(4, cores)), and prints one JSON line

    {"metric": "decode_scaling", "workers": N, "t1_ex_s": ..., "tn_ex_s":
     ..., "ratio": ..., "cores": ...}

CI uploads this as the decode-scaling artifact next to the bench smoke.
Exit code is 0 even for poor ratios on busy runners — the artifact records,
the perf-tier test (tests/test_pipeline_features.py) enforces.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import tpu_tfrecord.io as tfio
from tpu_tfrecord import _native
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType

SHARDS = int(os.environ.get("TFR_SCALING_SHARDS", 8))
ROWS_PER_SHARD = int(os.environ.get("TFR_SCALING_ROWS", 20_000))
WORKERS = int(os.environ.get("TFR_SCALING_WORKERS", 0)) or min(
    4, os.cpu_count() or 1
)
BATCH = 8192
if SHARDS * ROWS_PER_SHARD < 2 * BATCH:
    raise SystemExit(
        f"TFR_SCALING_SHARDS*TFR_SCALING_ROWS = {SHARDS * ROWS_PER_SHARD} "
        f"rows yields < 2 batches of {BATCH} (warmup consumes one; the "
        f"measurement needs at least one more) — raise the knobs"
    )

SCHEMA = StructType(
    [StructField("label", LongType(), nullable=False)]
    + [StructField(f"I{i}", LongType()) for i in range(1, 14)]
    + [StructField(f"C{i}", StringType()) for i in range(1, 27)]
)


def make_dataset(out: str) -> None:
    rng = np.random.default_rng(7)
    for _ in range(SHARDS):
        ints = rng.integers(0, 1 << 30, size=(ROWS_PER_SHARD, 14))
        cats = rng.integers(0, 1 << 24, size=(ROWS_PER_SHARD, 26))
        rows = [
            [int(v) for v in ints[r]] + [f"{v:08x}" for v in cats[r]]
            for r in range(ROWS_PER_SHARD)
        ]
        tfio.write(rows, SCHEMA, out, mode="append")


def run(out: str, workers: int, **ds_kw) -> float:
    """Sustained decode throughput (ex/s), first batch excluded (warmup)."""
    ds = TFRecordDataset(
        out, batch_size=BATCH, schema=SCHEMA, num_workers=workers, **ds_kw
    )
    with ds.batches() as it:
        next(it)
        t0 = time.perf_counter()
        n = 0
        for b in it:
            n += b.num_rows
        dt = time.perf_counter() - t0
    return n / dt


def main() -> None:
    if not _native.available():
        print(json.dumps({"metric": "decode_scaling", "skipped": "no native"}))
        return
    with tempfile.TemporaryDirectory(prefix="tfr_scaling_") as d:
        out = os.path.join(d, "ds")
        make_dataset(out)
        t1 = max(run(out, 1), run(out, 1))
        tn = max(run(out, WORKERS), run(out, WORKERS))
        # Cached-read series (ISSUE 4): the mmap-served columnar epoch
        # cache replaces decode entirely, so its single-worker rate is the
        # ceiling decode-worker scaling chases — tn approaching tc means
        # more workers only re-derive what one cache pass serves for free.
        cache_kw = dict(cache="auto", cache_dir=os.path.join(d, "cache"))
        run(out, 1, **cache_kw)  # populate pass (decode + cache append)
        tc = max(run(out, 1, **cache_kw), run(out, 1, **cache_kw))
    print(
        json.dumps(
            {
                "metric": "decode_scaling",
                "workers": WORKERS,
                "t1_ex_s": round(t1),
                "tn_ex_s": round(tn),
                "ratio": round(tn / t1, 3),
                "cached_ex_s": round(tc),
                "cached_vs_t1": round(tc / t1, 3),
                "cores": os.cpu_count(),
            }
        )
    )


if __name__ == "__main__":
    main()
