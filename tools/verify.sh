#!/usr/bin/env bash
# One-entry-point verification: the fast syntax gate plus the tier-1 test
# command from ROADMAP.md (keep the pytest invocation in sync with it).
# Usage: tools/verify.sh  (from the repo root or anywhere)
set -u
cd "$(dirname "$0")/.."

echo "== syntax gate (compileall) =="
python -m compileall -q tpu_tfrecord || exit 1

echo "== tfrecord_doctor self-check =="
# Write a shard, flip one byte, assert the doctor reports exactly one bad
# frame and that --repair round-trips every other record — so the salvage
# CLI can't rot.
env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, os, subprocess, sys, tempfile

from tpu_tfrecord import wire

tmp = tempfile.mkdtemp(prefix="tfr_doctor_check_")
shard = os.path.join(tmp, "self.tfrecord")
recs = [f"record-{i:03d}-".encode() * 3 for i in range(20)]
wire.write_records(shard, recs)
raw = bytearray(open(shard, "rb").read())
raw[len(raw) // 2] ^= 0xFF  # one flipped byte mid-file
open(shard, "wb").write(bytes(raw))

out = subprocess.run(
    [sys.executable, "tools/tfrecord_doctor.py", "--repair", shard],
    capture_output=True, text=True,
)
assert out.returncode == 1, (out.returncode, out.stdout, out.stderr)
lines = [json.loads(l) for l in out.stdout.splitlines() if l.strip()]
summary = [l for l in lines if l.get("event") == "summary"][0]
assert summary["corrupt_events"] == 1, lines
got = list(wire.read_records(summary["repaired_path"]))
assert len(got) == 19 and all(r in recs for r in got), len(got)
print("doctor self-check OK:", json.dumps(summary))
PY

echo "== tier-1 tests =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
