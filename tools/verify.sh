#!/usr/bin/env bash
# One-entry-point verification: the fast syntax gate plus the tier-1 test
# command from ROADMAP.md (keep the pytest invocation in sync with it).
# Usage: tools/verify.sh  (from the repo root or anywhere)
set -u
cd "$(dirname "$0")/.."

echo "== syntax gate (compileall) =="
python -m compileall -q tpu_tfrecord || exit 1

echo "== graftlint gate (AST invariants vs the committed baseline) =="
# Zero non-baselined findings over tpu_tfrecord/ tools/ examples/: clock
# discipline in policy modules, atomic persisted writes, the Metrics lock
# contract + lock-order graph, exception-swallow audit, and the metric
# vocabulary (call sites AND the README block). The HLO collective
# contracts (tools/graftlint/hlo_contracts.py) are compiled by the
# migrated pins inside the tier-1 run below.
python -m tools.graftlint || exit 1

echo "== tfrecord_doctor self-check =="
# Write a shard, flip one byte, assert the doctor reports exactly one bad
# frame and that --repair round-trips every other record — so the salvage
# CLI can't rot.
env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, os, subprocess, sys, tempfile

from tpu_tfrecord import wire

tmp = tempfile.mkdtemp(prefix="tfr_doctor_check_")
shard = os.path.join(tmp, "self.tfrecord")
recs = [f"record-{i:03d}-".encode() * 3 for i in range(20)]
wire.write_records(shard, recs)
raw = bytearray(open(shard, "rb").read())
raw[len(raw) // 2] ^= 0xFF  # one flipped byte mid-file
open(shard, "wb").write(bytes(raw))

out = subprocess.run(
    [sys.executable, "tools/tfrecord_doctor.py", "--repair", shard],
    capture_output=True, text=True,
)
assert out.returncode == 1, (out.returncode, out.stdout, out.stderr)
lines = [json.loads(l) for l in out.stdout.splitlines() if l.strip()]
summary = [l for l in lines if l.get("event") == "summary"][0]
assert summary["corrupt_events"] == 1, lines
got = list(wire.read_records(summary["repaired_path"]))
assert len(got) == 19 and all(r in recs for r in got), len(got)
print("doctor self-check OK:", json.dumps(summary))
PY

echo "== chaos smoke (seeded stall -> deadline -> skip_shard) =="
# One seeded stall scenario end-to-end: a shard whose read() hangs is
# converted by the read deadline into a skip_shard, the epoch COMPLETES,
# and the fault fires exactly as planned (ledger-checked) — so the
# stall-defense layer can't rot. The injected stall is bounded and the
# deadline is 100ms: the whole step costs well under a second.
env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, os, tempfile

import tpu_tfrecord.io as tfio
from tpu_tfrecord.faults import FaultPlan, FaultRule, install_chaos
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.schema import LongType, StructField, StructType

schema = StructType([StructField("id", LongType(), nullable=False)])
out = os.path.join(tempfile.mkdtemp(prefix="tfr_chaos_smoke_"), "ds")
for s in range(3):
    tfio.write([[i] for i in range(s * 20, (s + 1) * 20)], schema, out,
               mode="append" if s else "overwrite")
victim = sorted(n for n in os.listdir(out) if n.startswith("part-"))[0]
plan = FaultPlan([FaultRule(op="read", kind="stall", path=victim,
                            times=None, stall_ms=60_000)], seed=1)
ds = TFRecordDataset(out, batch_size=5, schema=schema, drop_remainder=False,
                     read_deadline_ms=100, on_stall="skip_shard",
                     use_mmap=False)
METRICS.reset()
got = []
with install_chaos(plan):
    with ds.batches() as it:
        for cb in it:
            got.extend(cb["id"].values.tolist())
plan.release()
assert METRICS.counter("read.stalls") >= 1, "no stall detected"
assert METRICS.counter("read.skipped_shards") == 1, "stalled shard not skipped"
assert len(got) == 40 and len(set(got)) == 40, (len(got), "epoch incomplete")
assert plan.ledger and plan.ledger[0]["kind"] == "stall", plan.ledger
print("chaos smoke OK:", json.dumps({
    "rows": len(got),
    "stalls": METRICS.counter("read.stalls"),
    "skipped_shards": METRICS.counter("read.skipped_shards"),
    "ledger_events": len(plan.ledger),
}))
PY

echo "== cache smoke (populate -> mmap-served epoch -> corrupt fallback) =="
# Write a dataset, run two epochs with cache="auto", assert the second
# (cache-served) epoch's rows are byte-identical with cache.hits > 0, then
# flip one byte inside a cache section and assert exactly one
# cache.corrupt_fallbacks with ground-truth rows — so the epoch cache
# can't rot.
env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, os, tempfile

import tpu_tfrecord.io as tfio
from tpu_tfrecord import cache as cache_mod
from tpu_tfrecord.columnar import batch_to_rows
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType

schema = StructType([StructField("id", LongType(), nullable=False),
                     StructField("s", StringType())])
base = tempfile.mkdtemp(prefix="tfr_cache_smoke_")
out = os.path.join(base, "ds"); cdir = os.path.join(base, "cache")
tfio.write([[i, f"s{i}"] for i in range(60)], schema, out, mode="overwrite")

def epoch_rows():
    ds = TFRecordDataset(out, batch_size=7, schema=schema, drop_remainder=False,
                         cache="auto", cache_dir=cdir)
    with ds.batches() as it:
        return [r for b in it for r in batch_to_rows(b, ds.schema)]

METRICS.reset()
ep1 = epoch_rows()          # populate
ep2 = epoch_rows()          # mmap-served
assert ep1 == ep2 and len(ep1) == 60, "epoch-2 rows differ from epoch-1"
assert METRICS.counter("cache.hits") > 0, "no cache hit on epoch 2"
entry = [os.path.join(cdir, n) for n in os.listdir(cdir)
         if n.endswith(cache_mod.ENTRY_SUFFIX)][0]
off = cache_mod.load_footer(entry)["chunks"][0]["columns"][0]["sections"][0][1]["off"]
raw = bytearray(open(entry, "rb").read()); raw[off] ^= 0xFF
open(entry, "wb").write(bytes(raw))
METRICS.reset()
ep3 = epoch_rows()          # corrupt entry -> ground-truth decode + rewrite
assert ep3 == ep1, "corrupt-cache fallback rows differ from ground truth"
assert METRICS.counter("cache.corrupt_fallbacks") == 1, \
    METRICS.counter("cache.corrupt_fallbacks")
print("cache smoke OK:", json.dumps({
    "rows": len(ep3),
    "hits": METRICS.counter("cache.hits"),
    "corrupt_fallbacks": METRICS.counter("cache.corrupt_fallbacks"),
}))
PY

echo "== telemetry smoke (trace -> Chrome trace + pulse + doctor report) =="
# One traced read end-to-end: the exported trace parses and contains decode
# spans, one pulse line parses, and the bottleneck doctor exits 0 with a
# verdict — so the flight recorder can't rot. All device-free, < 2s.
env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, os, subprocess, sys, tempfile

import tpu_tfrecord.io as tfio
from tpu_tfrecord import telemetry
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.schema import LongType, StructField, StructType
from tpu_tfrecord.telemetry import Pulse

schema = StructType([StructField("id", LongType(), nullable=False)])
out = os.path.join(tempfile.mkdtemp(prefix="tfr_tele_smoke_"), "ds")
tfio.write([[i] for i in range(120)], schema, out, mode="overwrite")

METRICS.reset(); telemetry.RECORDER.clear()
pulses = []
pulse = Pulse(0.05, emit=pulses.append).start()
ds = TFRecordDataset(out, batch_size=16, schema=schema, drop_remainder=False,
                     trace="on")
with ds.batches() as it:
    rows = sum(b.num_rows for b in it)
pulse.stop()  # final tick guarantees at least one line
telemetry.disable()
assert rows == 120, rows
trace = json.loads(json.dumps(telemetry.RECORDER.to_chrome_trace()))
decode = [e for e in trace["traceEvents"] if e["name"] == "decode"]
assert decode, "no decode spans in exported trace"
assert all("ts" in e and "dur" in e for e in decode), decode[0]
line = json.loads(json.dumps(pulses[-1]))
assert line["event"] == "pulse" and "verdict" in line, line

doc = subprocess.run([sys.executable, "tools/tfrecord_doctor.py", "report",
                      out, "--batches", "4", "--batch-size", "16"],
                     capture_output=True, text=True)
assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
lines = [json.loads(l) for l in doc.stdout.splitlines() if l.strip()]
report = [l for l in lines if l.get("event") == "report"][0]
assert report.get("verdict"), report
print("telemetry smoke OK:", json.dumps({
    "decode_spans": len(decode),
    "pulse_lines": len(pulses),
    "doctor_verdict": report["verdict"],
}))
PY

echo "== autotune smoke (seeded throttle -> pool grows -> identical rows) =="
# One closed-loop scenario end-to-end: every shard read pays a seeded
# 25ms injected stall, autotune starts from deliberately-wrong knobs
# (1 worker, depth-1 prefetch), the controller must GROW the decode pool
# at pulse boundaries (autotune.adjustments counters prove it), and the
# rows must be byte-identical to a fixed-knob run — so the autotuner
# can't rot. Bounded stalls + fast pulses: a few seconds total.
env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, os, tempfile

import tpu_tfrecord.io as tfio
from tpu_tfrecord.faults import FaultPlan, FaultRule, install_chaos
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.schema import LongType, StructField, StructType

schema = StructType([StructField("id", LongType(), nullable=False)])
out = os.path.join(tempfile.mkdtemp(prefix="tfr_autotune_smoke_"), "ds")
for s in range(6):
    tfio.write([[i] for i in range(s * 30, (s + 1) * 30)], schema, out,
               mode="append" if s else "overwrite")

def run(**kw):
    # fresh registry per leg: the controller reads process-global
    # quantiles/gauges, which must describe ITS run, not the previous leg
    METRICS.reset()
    plan = FaultPlan([FaultRule(op="read", kind="stall", path="part-",
                                times=None, stall_ms=25)], seed=3)
    ds = TFRecordDataset(out, batch_size=10, schema=schema,
                         drop_remainder=False, num_epochs=8,
                         use_mmap=False, **kw)
    rows = []
    with install_chaos(plan):
        with ds.batches() as it:
            tuner = it.autotune
            for cb in it:
                rows.extend(cb["id"].values.tolist())
    plan.release()
    return rows, tuner

fixed_rows, _ = run(num_workers=4, prefetch=4)
tuned_rows, tuner = run(num_workers=1, prefetch=1,
                        autotune="on", autotune_interval_s=0.1)
assert tuned_rows == fixed_rows, "autotuned rows differ from fixed-knob run"
grows = [d for d in tuner.log if d["knob"] == "workers" and d["to"] > d["from"]]
assert grows, f"controller never grew the pool: {tuner.log}"
assert METRICS.counter("autotune.adjustments") >= len(tuner.log) > 0
assert METRICS.gauge_value("autotune.workers", 0) > 1
print("autotune smoke OK:", json.dumps({
    "rows": len(tuned_rows),
    "adjustments": METRICS.counter("autotune.adjustments"),
    "final_workers": tuner.control.workers,
    "trajectory": [(d["knob"], d["from"], d["to"]) for d in tuner.log],
}))
PY

echo "== fleet smoke (3 spooling readers -> exact aggregation + fleet doctor + merged trace) =="
# Three short-lived reader subprocesses spool into one directory while a
# shared trace context propagates via TFR_TRACE_CONTEXT: the aggregator's
# merged read decode count must equal the SUM of the per-process counts
# exactly, `tfrecord_doctor fleet` must exit 0 with a verdict, and the
# merged Chrome trace must parse with >= 3 distinct pid tracks — so the
# cluster flight recorder can't rot.
env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, os, subprocess, sys, tempfile

import tpu_tfrecord.io as tfio
from tpu_tfrecord import fleet, telemetry
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType

schema = StructType([StructField("id", LongType(), nullable=False),
                     StructField("s", StringType())])
root = tempfile.mkdtemp(prefix="tfr_fleet_smoke_")
out = os.path.join(root, "ds")
for s in range(3):
    tfio.write([[i, f"s{i}"] for i in range(s * 40, (s + 1) * 40)],
               schema, out, mode="append" if s else "overwrite")

spool = os.path.join(root, "spool")
ctx = telemetry.TraceContext.new(role="verify")
env = {**os.environ, "JAX_PLATFORMS": "cpu", **ctx.to_env()}
traces = [os.path.join(root, f"trace-{i}.json") for i in range(3)]
procs = [subprocess.Popen(
    [sys.executable, "tests/fleet_worker.py", out, spool,
     "--role", f"reader{i}", "--epochs", "2", "--interval", "0.1",
     "--trace-out", traces[i]],
    stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
) for i in range(3)]
outs = []
for p in procs:
    o, e = p.communicate(timeout=240)
    assert p.returncode == 0, (p.returncode, o, e)
    outs.append(json.loads(o.splitlines()[-1]))
assert {o["trace_id"] for o in outs} == {ctx.trace_id}, outs

snap = fleet.TelemetryAggregator(spool).aggregate()
per_proc = sum(o["decode_records"] for o in outs)
assert len(snap.processes) == 3, [p.path for p in snap.processes]
assert snap.stages["decode"][0] == per_proc, \
    (snap.stages["decode"], per_proc)

doc = subprocess.run([sys.executable, "tools/tfrecord_doctor.py", "fleet",
                      spool, "--stale-after", "3600"],
                     capture_output=True, text=True)
assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
lines = [json.loads(l) for l in doc.stdout.splitlines() if l.strip()]
fleet_line = [l for l in lines if l.get("event") == "fleet"][0]
assert fleet_line.get("verdict"), fleet_line

merged_path = os.path.join(root, "merged.json")
mt = subprocess.run([sys.executable, "tools/tfrecord_doctor.py",
                     "merge-trace", merged_path] + traces,
                    capture_output=True, text=True)
assert mt.returncode == 0, (mt.returncode, mt.stdout, mt.stderr)
doc = json.load(open(merged_path))
pids = {e["pid"] for e in doc["traceEvents"]}
assert len(pids) >= 3, pids
named = {e["pid"] for e in doc["traceEvents"]
         if e.get("ph") == "M" and e["name"] == "process_name"}
assert pids <= named, (pids, named)
print("fleet smoke OK:", json.dumps({
    "decode_sum": per_proc,
    "doctor_verdict": fleet_line["verdict"],
    "merged_pid_tracks": len(pids),
}))
PY

echo "== service smoke (3 workers + 1 consumer + worker SIGKILL -> exactly-once) =="
# Three decode-worker subprocesses leased by an in-process dispatcher feed
# one consumer; mid-epoch the worker HOLDING the active lease is SIGKILLed.
# The epoch must complete with rows byte-identical to a direct local read
# (exactly-once: nothing duplicated, nothing missing), the dispatcher must
# count exactly one lease reassignment, no shard may fall back to local
# reads, and `tfrecord_doctor serve-status` must exit 0 — so the
# disaggregated data service can't rot.
env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, os, signal, subprocess, sys, tempfile, time

import tpu_tfrecord.io as tfio
from tpu_tfrecord import service
from tpu_tfrecord.columnar import batch_to_rows
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType

schema = StructType([StructField("id", LongType(), nullable=False),
                     StructField("s", StringType())])
out = os.path.join(tempfile.mkdtemp(prefix="tfr_service_smoke_"), "ds")
for s in range(6):
    tfio.write([[i, f"s{i}"] for i in range(s * 30, (s + 1) * 30)],
               schema, out, mode="append" if s else "overwrite")

def epoch_rows(**kw):
    ds = TFRecordDataset(out, batch_size=8, schema=schema,
                         drop_remainder=False, **kw)
    rows = []
    with ds.batches() as it:
        for b in it:
            rows.extend(batch_to_rows(b, ds.schema))
            yield_hook(rows, ds)
    return rows

yield_hook = lambda rows, ds: None
local = epoch_rows()

d = service.ServiceDispatcher(lease_ttl_s=10.0).start()
env = {**os.environ, "JAX_PLATFORMS": "cpu"}
procs = {}

# a failed assert anywhere below must not leak worker subprocesses (their
# heartbeat loops retry the dead dispatcher forever); the clean
# terminate/wait path at the bottom still runs first on success
import atexit
def _reap():
    for p in procs.values():
        if p.poll() is None:
            p.kill()
atexit.register(_reap)
for _ in range(3):
    p = subprocess.Popen(
        [sys.executable, "-m", "tpu_tfrecord.service", "worker",
         "--dispatcher", d.addr],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True, env=env,
    )
    ready = json.loads(p.stdout.readline())
    procs[ready["worker_id"]] = p
deadline = time.monotonic() + 60
while time.monotonic() < deadline and len(d.status()["workers"]) < 3:
    time.sleep(0.05)
assert len(d.status()["workers"]) == 3, d.status()

# Warm epoch: each worker's FIRST fetch pays dataset construction
# (seconds on a loaded box), which must not be mistaken for a dead
# worker by the kill epoch below.
warm = epoch_rows(service=d.addr, service_deadline_ms=10000)
assert warm == local, "warm service epoch rows differ from direct local read"
assert d.status()["lease_reassignments"] == 0, d.status()

killed = []
def yield_hook(rows, ds):
    if killed or len(rows) < 40:
        return
    holders = [w["worker_id"] for w in d.status()["workers"] if w["leases"]]
    if holders:  # SIGKILL whoever is serving the consumer RIGHT NOW
        victim = procs[holders[0]]
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait()
        killed.append(holders[0])

METRICS.reset()
got = epoch_rows(service=d.addr, service_deadline_ms=10000)
assert killed, "no active lease ever observed — nothing was killed"
assert got == local, "service epoch rows differ from direct local read"
st = d.status()
assert st["lease_reassignments"] == 1, st
assert METRICS.counter("service.fallbacks") == 0, "degraded to local reads"

doc = subprocess.run([sys.executable, "tools/tfrecord_doctor.py",
                      "serve-status", d.addr],
                     capture_output=True, text=True)
assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
lines = [json.loads(l) for l in doc.stdout.splitlines() if l.strip()]
summary = [l for l in lines if l.get("event") == "service"][0]
assert summary["lease_reassignments"] == 1, summary

for p in procs.values():
    if p.poll() is None:
        p.terminate()
for p in procs.values():
    if p.poll() is None:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
d.stop()
print("service smoke OK:", json.dumps({
    "rows": len(got),
    "killed_worker": killed[0],
    "lease_reassignments": st["lease_reassignments"],
    "reconnects": METRICS.counter("service.reconnects"),
}))
PY

echo "== elastic smoke (throttled fleet grows -> drains on idle -> identical rows) =="
# The elastic service layer end-to-end, production-shaped: the FleetScaler
# brings up ONE decode-worker subprocess (below-min refill), every worker
# read pays a seeded 25ms injected stall (--fault-plan), so the consumer's
# spool says producer_bound and the scaler must GROW the fleet mid-run;
# when the consumer closes (load removed) the verdict goes idle and the
# scaler must DRAIN back to 1 worker via clean goodbyes. Rows must be
# byte-identical throughout, and serve-status (with its new tenant +
# scaler lines) must exit 0 — so the elastic layer can't rot.
env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, os, subprocess, sys, tempfile, time

import tpu_tfrecord.io as tfio
from tpu_tfrecord import elastic, service
from tpu_tfrecord.columnar import batch_to_rows
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.schema import LongType, StructField, StructType

schema = StructType([StructField("id", LongType(), nullable=False)])
root = tempfile.mkdtemp(prefix="tfr_elastic_smoke_")
out = os.path.join(root, "ds")
for s in range(6):
    tfio.write([[i] for i in range(s * 30, (s + 1) * 30)], schema, out,
               mode="append" if s else "overwrite")

def epoch_rows(**kw):
    ds = TFRecordDataset(out, batch_size=10, schema=schema,
                         drop_remainder=False, **kw)
    with ds.batches() as it:
        return [r for b in it for r in batch_to_rows(b, ds.schema)]

local = epoch_rows(num_epochs=1)

plan_path = os.path.join(root, "plan.json")
with open(plan_path, "w") as fh:
    json.dump({"seed": 3, "rules": [{"op": "read", "kind": "stall",
                                     "path": "part-", "times": None,
                                     "stall_ms": 25}]}, fh)
spool = os.path.join(root, "spool")
d = service.ServiceDispatcher(lease_ttl_s=2.0).start()
spawner = elastic.SubprocessSpawner(
    d.addr, ("--fault-plan", plan_path, "--drain-grace", "0.2"),
    env={**os.environ, "JAX_PLATFORMS": "cpu"})
scaler = elastic.FleetScaler(
    d, spawner, spool_dir=spool,
    policy=elastic.ScalerPolicy(hysteresis=2, cooldown_s=0.4,
                                min_workers=1, max_workers=3),
    interval_s=0.2).start()
try:
    # the scaler itself brings up worker 1 (below-min refill)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and d.status()["alive"] < 1:
        time.sleep(0.05)
    assert d.status()["alive"] >= 1, d.status()

    # OFFERED LOAD: 8 epochs through the service, every worker-side read
    # under the seeded 25ms stall -> producer_bound -> the fleet GROWS
    rows = epoch_rows(num_epochs=8, service=d.addr,
                      service_deadline_ms=15000,
                      telemetry_spool_dir=spool, spool_interval_s=0.1)
    assert rows == local * 8, "elastic service rows differ from local"
    ups = METRICS.counter("elastic.scale_ups")  # scaler is in-process
    grows = [x for x in scaler.log if x["action"] == "scale_up"
             and x["reason"] == "producer_bound"]
    assert grows, f"scaler never grew the fleet: {scaler.log}"
    peak = max(x["target"] for x in grows)
    assert peak >= 2, scaler.log

    # LOAD REMOVED: consumer closed (its spool says final) -> idle ->
    # the scaler drains the fleet back to the 1-worker floor
    active = []
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        active = [w for w in d.status()["workers"]
                  if w["alive"] and not w["draining"]]
        if len(active) == 1:
            break
        time.sleep(0.2)
    assert len(active) == 1, d.status()
    drains = [x for x in scaler.log if x["action"] == "scale_down"
              and x["reason"] == "idle"]
    assert drains, scaler.log

    doc = subprocess.run([sys.executable, "tools/tfrecord_doctor.py",
                          "serve-status", d.addr],
                         capture_output=True, text=True)
    assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
    lines = [json.loads(l) for l in doc.stdout.splitlines() if l.strip()]
    assert [l for l in lines if l.get("event") == "scaler"], lines
    assert [l for l in lines if l.get("event") == "tenant"], lines
finally:
    scaler.stop()
    spawner.reap()
    d.stop()
print("elastic smoke OK:", json.dumps({
    "rows": len(rows),
    "peak_workers": peak,
    "scale_ups": ups,
    "scale_downs": METRICS.counter("elastic.scale_downs"),
}))
PY

echo "== remote smoke (real HTTP backend + seeded resets/stalls/truncation -> byte-identical epoch) =="
# Serve a local dataset through the threaded Range server, fire a seeded
# plan mixing connection resets, a server-side stall, a truncated body,
# and a 503 — all at the real socket — and assert one epoch with retries
# is byte-identical to the local read with zero corrupt rows and the
# fault ledger populated. Then tfrecord_doctor scans an http:// source.
env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, os, subprocess, sys, tempfile

import tpu_tfrecord.io as tfio
from tpu_tfrecord import httpfs
from tpu_tfrecord.faults import FaultPlan, FaultRule
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.retry import RetryPolicy
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType

schema = StructType([StructField("id", LongType(), nullable=False),
                     StructField("s", StringType())])
root = tempfile.mkdtemp(prefix="tfr_remote_smoke_")
out = os.path.join(root, "ds")
for s in range(3):
    tfio.write([[i, f"s{i}"] for i in range(s * 60, (s + 1) * 60)],
               schema, out, mode="append" if s else "overwrite")
names = sorted(n for n in os.listdir(out) if n.startswith("part-"))

def read_ids(src, **kw):
    ds = TFRecordDataset(src, batch_size=16, schema=schema,
                         drop_remainder=False, **kw)
    with ds.batches() as it:
        return [i for cb in it for i in cb["id"].values.tolist()]

local = read_ids(out)
plan = FaultPlan([
    FaultRule(op="http", kind="reset", path=names[0], cap_bytes=128, times=1),
    FaultRule(op="http", kind="stall", path=names[1], stall_ms=50, times=1),
    FaultRule(op="http", kind="truncated_body", path=names[1], cap_bytes=90,
              times=1),
    FaultRule(op="http", kind="http_error", path=names[2], status=503,
              retry_after_s=0.01, times=1),
], seed=9)
with httpfs.serve_directory(root, plan=plan) as srv:
    METRICS.reset()
    got = read_ids(srv.url_for("ds"),
                   retry_policy=RetryPolicy(max_retries=3,
                                            sleep=lambda _s: None))
    assert got == local, "remote epoch differs from local read"
    assert METRICS.counter("read.retries") > 0, "no retry ever fired"
    assert METRICS.counter("read.corrupt_records") == 0, "corrupt rows leaked"
    kinds = sorted(e["kind"] for e in plan.ledger)
    assert kinds == ["http_error", "reset", "stall", "truncated_body"], kinds

    doc = subprocess.run(
        [sys.executable, "tools/tfrecord_doctor.py",
         srv.url_for("ds/" + names[0])],
        capture_output=True, text=True)
    assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
    lines = [json.loads(l) for l in doc.stdout.splitlines() if l.strip()]
    summary = [l for l in lines if l.get("event") == "summary"][0]
    assert summary["records"] == 60 and summary["corrupt_events"] == 0, summary
print("remote smoke OK:", json.dumps({
    "rows": len(got),
    "retries": METRICS.counter("read.retries"),
    "ledger_kinds": kinds,
    "doctor_records": summary["records"],
}))
PY

echo "== LM smoke (8-device mesh, kill -9 mid-run, resume -> byte-identical data order + continued loss) =="
# Train the causal LM (zigzag ring attention, dp x sp on the 8-device CPU
# mesh) twice over the same generated dataset: once uninterrupted, once
# SIGKILLed the moment step 10 is logged and then resumed from its last
# atomic checkpoint (step 8). The resumed leg's packed-batch digests must
# equal the uninterrupted run's for every overlapping step (byte-identical
# data order) and its losses must continue the same curve exactly — so the
# model-parallel consumer path can't rot.
env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python - <<'PY' || exit 1
import json, os, signal, subprocess, sys, tempfile

root = tempfile.mkdtemp(prefix="tfr_lm_smoke_")
data = os.path.join(root, "data")
def run(ck, digests, extra=(), kill_at=None):
    cmd = [sys.executable, "examples/train_lm.py", "--steps", "16",
           "--save-every", "4", "--data-dir", data, "--ckpt-dir", ck,
           "--digest-out", digests, *extra]
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    out = []
    for line in p.stdout:
        out.append(line)
        if kill_at is not None and line.startswith("lm_step"):
            if json.loads(line.split(" ", 1)[1])["step"] >= kill_at:
                os.kill(p.pid, signal.SIGKILL)
                break
    p.wait()
    if kill_at is None:
        assert p.returncode == 0, (p.returncode, "".join(out)[-2000:])
    return "".join(out)

def load(path):
    return {json.loads(l)["step"]: json.loads(l) for l in open(path)}

a_digests = os.path.join(root, "a.jsonl")
run(os.path.join(root, "ck_a"), a_digests)                       # reference
b_digests = os.path.join(root, "b.jsonl")
run(os.path.join(root, "ck_b"), b_digests, kill_at=10)           # killed
resumed = run(os.path.join(root, "ck_b"), b_digests)             # resumed
# the SIGKILL fires after the step-10 line, so the surviving checkpoint is
# step 8 — or step 12 if the child squeezed past the next save boundary
# before the signal landed; derive the actual resume point, require a real
# mid-run resume either way
import re
m = re.search(r"resumed at step (\d+)", resumed)
assert m, resumed[-1500:]
rstep = int(m.group(1))
assert rstep in (8, 12), rstep
A, B = load(a_digests), load(b_digests)
overlap = sorted(s for s in A if s > rstep and s in B)
assert len(overlap) == 16 - rstep, (rstep, sorted(A), sorted(B))
for s in overlap:
    assert A[s]["digest"] == B[s]["digest"], (s, A[s], B[s])
    assert abs(float(A[s]["loss"]) - float(B[s]["loss"])) < 1e-6, (s, A[s], B[s])
losses = [float(A[s]["loss"]) for s in sorted(A)]
assert losses[-1] < losses[0], losses  # training signal, not noise
print("lm smoke OK:", json.dumps({
    "steps_compared": len(overlap),
    "first_loss": losses[0],
    "final_loss": losses[-1],
}))
PY

echo "== LM fsdp smoke (dp x fsdp weight sharding: same data, same loss as pure dp + HLO contract rows) =="
# The full-GSPMD-mesh leg (PR 19): train 8 steps under --mesh dp and
# --mesh dp_fsdp over the SAME generated dataset. Weight sharding is a
# layout choice, not a numerics choice: the packed-batch digests must be
# byte-identical and the per-step losses equal to float tolerance, the
# trainer must report its sharded per-device param bytes, and the two
# fsdp HLO contract rows (gather-on-use dp×fsdp, and dp×fsdp×pp composed
# under the pipeline's boundary reshard) must pass against live compiles.
env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python - <<'PY' || exit 1
import json, os, re, subprocess, sys, tempfile

root = tempfile.mkdtemp(prefix="tfr_lm_fsdp_smoke_")
data = os.path.join(root, "data")

def run(mesh, tag):
    digests = os.path.join(root, tag + ".jsonl")
    res = subprocess.run(
        [sys.executable, "examples/train_lm.py", "--mesh", mesh,
         "--steps", "8", "--save-every", "4", "--data-dir", data,
         "--ckpt-dir", os.path.join(root, "ck_" + tag),
         "--digest-out", digests],
        capture_output=True, text=True, timeout=600,
    )
    assert res.returncode == 0, (res.returncode, res.stdout[-2000:],
                                 res.stderr[-1000:])
    lines = {json.loads(l)["step"]: json.loads(l) for l in open(digests)}
    return res.stdout, lines

_, dp = run("dp", "dp")
out_f, fsdp = run("dp_fsdp", "fsdp")
m = re.search(r"fsdp param bytes/device: (\d+)", out_f)
assert m, out_f[-1500:]
per_dev = int(m.group(1))
assert "'fsdp': 4" in out_f, out_f[-1500:]
assert sorted(dp) == sorted(fsdp) == list(range(1, 9)), (sorted(dp), sorted(fsdp))
for s in dp:
    assert dp[s]["digest"] == fsdp[s]["digest"], (s, dp[s], fsdp[s])
    d = abs(float(dp[s]["loss"]) - float(fsdp[s]["loss"]))
    assert d < 5e-4, (s, dp[s], fsdp[s])

from tools.graftlint import hlo_contracts
for row in ("lm_train_step_fsdp", "lm_train_step_fsdp_pp"):
    hlo_contracts.verify(row)
print("lm fsdp smoke OK:", json.dumps({
    "steps_compared": len(dp),
    "fsdp_param_bytes_per_device": per_dev,
    "contract_rows": ["lm_train_step_fsdp", "lm_train_step_fsdp_pp"],
}))
PY

echo "== serving smoke (train_lm dp_pp interleaved -> serve_lm streams the checkpoint byte-identically) =="
# The inference path end-to-end (ISSUE 15): train the LM on the dp×pp
# interleaved mesh (2 stages × 2 virtual chunks), leave its atomic
# checkpoint behind, then serve N streamed microbatches through LMStream.
# serve_lm itself asserts the streamed logits equal the batch path
# (batch-mode pipeline_apply on the same slices) BITWISE; here we pin
# that it exits 0, reports that byte-identity, and lands a requests/s
# number — so the serving surface can't rot.
env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python - <<'PY' || exit 1
import json, os, subprocess, sys, tempfile

root = tempfile.mkdtemp(prefix="tfr_serve_smoke_")
data, ck = os.path.join(root, "data"), os.path.join(root, "ckpt")
res = subprocess.run(
    [sys.executable, "examples/train_lm.py", "--mesh", "dp_pp",
     "--virtual", "2", "--steps", "8", "--save-every", "4",
     "--data-dir", data, "--ckpt-dir", ck],
    capture_output=True, text=True, timeout=600,
)
assert res.returncode == 0, (res.returncode, res.stdout[-2000:], res.stderr[-1000:])
# the async generation layout: newest COMPLETE generation carries the
# manifest committed last
assert os.path.exists(os.path.join(ck, "gen-00000008", "MANIFEST.json")), \
    os.listdir(ck)

srv = subprocess.run(
    [sys.executable, "examples/serve_lm.py", "--ckpt-dir", ck,
     "--pipe", "2", "--virtual", "2", "--requests", "12"],
    capture_output=True, text=True, timeout=600,
)
assert srv.returncode == 0, (srv.returncode, srv.stdout[-2000:], srv.stderr[-1000:])
line = [l for l in srv.stdout.splitlines() if l.startswith("serve_lm OK:")]
assert line, srv.stdout[-2000:]
rep = json.loads(line[0].split("serve_lm OK:", 1)[1])
assert rep["byte_identical_to_batch"] is True, rep
assert rep["requests"] == 12 and rep["requests_per_s"] > 0, rep
assert rep["ckpt_step"] == 8, rep
print("serving smoke OK:", json.dumps({
    "requests_per_s": rep["requests_per_s"],
    "latency_ms_p50": rep["latency_ms_p50"],
    "byte_identical": rep["byte_identical_to_batch"],
}))
PY

echo "== serving-tier smoke (subprocess replica + injected disconnect -> 4 concurrent clients byte-identical to sequential; doctor serve verdict) =="
# ISSUE 18 end-to-end: one synthetic-model replica in its own process
# with a seeded op='serve' client_disconnect fault armed on the reply
# seam. 4 concurrent ServeClients multiplex onto the continuous-batching
# engine; the victim's connection is dropped mid-exchange and its client
# reconnects and resends (deterministic model => same bytes). Every
# client's output must be byte-identical to a one-at-a-time
# sequential_reference run, SIGTERM must drain gracefully (exit 0, final
# spool snapshot), and `tfrecord_doctor serve` on the spool must exit 0
# with the disconnect counted and a verdict.
env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python - <<'PY' || exit 1
import json, os, signal, subprocess, sys, tempfile, threading, time

import numpy as np

root = tempfile.mkdtemp(prefix="tfr_serve_tier_smoke_")
spool = os.path.join(root, "spool")
plan_path = os.path.join(root, "plan.json")
from tpu_tfrecord import faults
plan = faults.FaultPlan([
    faults.FaultRule(op="serve", kind="client_disconnect",
                     path="reply:", times=1),
])
with open(plan_path, "w") as fh:
    json.dump(plan.to_json(), fh)

srv = subprocess.Popen(
    [sys.executable, "-m", "tpu_tfrecord.serving", "--seed", "0",
     "--spool-dir", spool, "--fault-plan", plan_path],
    stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
)
try:
    ready = json.loads(srv.stdout.readline())
    addr = ready["addr"]

    rng = np.random.default_rng(7)
    windows = [
        rng.integers(1, 96, size=16).astype(np.int32) for _ in range(5)
    ]

    from tpu_tfrecord import service_protocol as sp
    from tpu_tfrecord.serving import ServeClient

    # phase 1 — the 4 concurrent clients, injected chaos armed: the
    # FIRST reply written on any connection is killed (times=1), so
    # exactly one client loses a completed reply and its retry policy
    # resends (the +1 in the doctor's request count below)
    results, errors = {}, []

    def client(i):
        c = ServeClient([addr])
        try:
            results[i] = c.generate(windows[i], n_new=3)
        except Exception as e:  # noqa: BLE001
            errors.append((i, repr(e)))
        finally:
            c.close()

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert sorted(results) == [0, 1, 2, 3], sorted(results)

    # phase 2 — a doomed raw-socket client (the injected fault is spent,
    # so status replies are safe now): long request, hang up the moment
    # the engine has it in flight — the dropped slot must free (counted
    # serve.disconnects) and the replica must still drain cleanly
    doomed = sp.connect(addr, timeout=30.0)
    sp.send_msg(doomed, {
        "v": sp.PROTO_VERSION, "op": "generate", "req": 1,
        "tokens": windows[4].tolist(), "n_new": 500, "deadline_s": None,
    })
    probe = sp.connect(addr, timeout=30.0)
    deadline = time.monotonic() + 60
    while True:
        st = sp.request(probe, addr, {
            "v": sp.PROTO_VERSION, "op": "status", "req": 1,
        })
        if st["in_flight"] >= 1:
            break
        assert time.monotonic() < deadline, st
        time.sleep(0.02)
    doomed.close()
    # the freed slot: in_flight drains back to 0 before the goodbye
    deadline = time.monotonic() + 60
    while True:
        st = sp.request(probe, addr, {
            "v": sp.PROTO_VERSION, "op": "status", "req": 2,
        })
        if st["in_flight"] == 0 and st["queue_depth"] == 0:
            break
        assert time.monotonic() < deadline, st
        time.sleep(0.05)
    assert st["counters"].get("serve.disconnects", 0) >= 1, st
    probe.close()

    # the local reference: same seed 0 => same params => exact bytes
    import jax
    from tpu_tfrecord.models import lm
    from tpu_tfrecord.serving import sequential_reference
    from tpu_tfrecord.tpu import create_mesh
    cfg = lm.LMConfig(vocab_size=96, d_model=32, n_heads=2, n_layers=4,
                      max_len=16, n_micro=4, n_virtual=1)
    params = lm.init_params(jax.random.key(0), cfg)
    mesh = create_mesh({"pipe": 2}, jax.devices()[:2])
    ref = sequential_reference(
        params, cfg, mesh, [(w, 3) for w in windows], 4
    )
    for i in range(4):
        assert results[i] == ref[i], (i, results[i], ref[i])

    srv.send_signal(signal.SIGTERM)  # graceful drain
    out, err = srv.communicate(timeout=60)
    assert srv.returncode == 0, (srv.returncode, out[-2000:], err[-2000:])
finally:
    if srv.poll() is None:
        srv.kill()
        srv.wait()

doc = subprocess.run(
    [sys.executable, "tools/tfrecord_doctor.py", "serve", spool, "--json"],
    capture_output=True, text=True, timeout=120,
)
assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
events = json.loads(doc.stdout)["events"]
summary = [e for e in events if e["event"] == "serve"][-1]
# 5 completed requests: 4 clients + ONE resend — the injected reply-seam
# disconnect killed exactly one completed reply and that client's retry
# policy resent it (deterministic model => same bytes). The doomed raw
# client's mid-generation hangup is the counted disconnect; the injected
# one dropped a COMPLETED request's reply, which is a resend, not lost
# work.
assert summary["requests"] == 5, summary
assert summary["sheds"]["disconnects"] >= 1, summary
assert summary["verdict"] in (
    "meeting_slo", "compute_bound", "queue_bound", "unknown"
), summary
print("serving-tier smoke OK:", json.dumps({
    "byte_identical": True,
    "disconnects": summary["sheds"]["disconnects"],
    "verdict": summary["verdict"],
    "latency_p99_ms": summary.get("latency_p99_ms"),
}))
PY

echo "== SLO + request-tracing smoke (traced replica, 4 clients + injected deadline expiry -> doctor slo burn verdict; merged trace has one serve.request per admitted request) =="
# ISSUE 20 end-to-end: a --trace-out replica under 4 concurrent clients
# plus ONE request submitted with an already-expired deadline. The spool's
# history must drive `tfrecord_doctor slo` to exit 0 with a burn-rate
# verdict on the availability objective (1 expiry against 4 completions
# burns far past the 14.4x fast threshold), and `merge-trace` pointed at
# the TRACE DIRECTORY must produce a timeline holding exactly one
# serve.request root span per admitted request, each with a
# serve.queue_wait child and >= 1 serve.tick slice under the same
# client-minted span id.
env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python - <<'PY' || exit 1
import json, os, signal, subprocess, sys, tempfile, threading

import numpy as np

root = tempfile.mkdtemp(prefix="tfr_slo_smoke_")
spool = os.path.join(root, "spool")
traces = os.path.join(root, "traces")
os.makedirs(traces)

srv = subprocess.Popen(
    [sys.executable, "-m", "tpu_tfrecord.serving", "--seed", "0",
     "--spool-dir", spool,
     "--trace-out", os.path.join(traces, "replica.json")],
    stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
)
try:
    ready = json.loads(srv.stdout.readline())
    addr = ready["addr"]

    from tpu_tfrecord import telemetry
    from tpu_tfrecord.serving import DeadlineExpired, ServeClient

    telemetry.enable()  # the client half of the merged timeline
    rng = np.random.default_rng(7)
    windows = [
        rng.integers(1, 96, size=16).astype(np.int32) for _ in range(4)
    ]
    results, errors = {}, []

    def client(i):
        c = ServeClient([addr])
        try:
            results[i] = c.generate(windows[i], n_new=3)
        except Exception as e:  # noqa: BLE001
            errors.append((i, repr(e)))
        finally:
            c.close()

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert sorted(results) == [0, 1, 2, 3], sorted(results)

    # the injected deadline expiry: already unmeetable at admission, so
    # it is REFUSED (never admitted -> no serve.request span) but counted
    # into serve.deadline_expired — the availability objective's burn
    expired = ServeClient([addr])
    try:
        expired.generate(windows[0], n_new=3, deadline_s=0.0)
        raise AssertionError("deadline_s=0 request was served")
    except DeadlineExpired:
        pass
    finally:
        expired.close()

    telemetry.RECORDER.save_chrome_trace(os.path.join(traces, "clients.json"))
    telemetry.disable()

    srv.send_signal(signal.SIGTERM)  # graceful drain -> final spool line
    out, err = srv.communicate(timeout=60)
    assert srv.returncode == 0, (srv.returncode, out[-2000:], err[-2000:])
finally:
    if srv.poll() is None:
        srv.kill()
        srv.wait()

# doctor slo: exit 0, the availability objective named, burning fast
doc = subprocess.run(
    [sys.executable, "tools/tfrecord_doctor.py", "slo", spool, "--json"],
    capture_output=True, text=True, timeout=120,
)
assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
events = json.loads(doc.stdout)["events"]
avail = [
    e for e in events
    if e["event"] == "objective" and e["kind"] == "availability"
]
assert len(avail) == 1, events
assert avail[0]["objective"] == "availability:0.999", avail
assert avail[0]["bad"] >= 1 and avail[0]["total"] >= 5, avail
assert avail[0]["verdict"] == "fast_burn", avail
summary = [e for e in events if e["event"] == "slo"][-1]
assert summary["verdict"] == "fast_burn", summary

# merge-trace on the DIRECTORY: one serve.request per admitted request,
# each with its queue_wait child and >= 1 tick slice
merged_path = os.path.join(root, "merged.json")
mt = subprocess.run(
    [sys.executable, "tools/tfrecord_doctor.py", "merge-trace",
     merged_path, traces],
    capture_output=True, text=True, timeout=120,
)
assert mt.returncode == 0, (mt.returncode, mt.stdout, mt.stderr)
with open(merged_path) as fh:
    merged = json.load(fh)
evs = merged["traceEvents"]
reqs = [e for e in evs if e.get("name") == "serve.request"]
assert len(reqs) == 4, [e.get("name") for e in evs][:40]
span_ids = {e["args"]["span_id"] for e in reqs}
assert len(span_ids) == 4, reqs
for sid in span_ids:
    kids = [
        e for e in evs
        if e.get("args", {}).get("parent_span_id") == sid
    ]
    names = [e["name"] for e in kids]
    assert "serve.queue_wait" in names, (sid, names)
    assert names.count("serve.tick") >= 1, (sid, names)
expiries = [e for e in evs if e.get("name") == "serve.deadline_expired"]
assert len(expiries) >= 1, "injected expiry left no instant"
print("slo smoke OK:", json.dumps({
    "availability_verdict": avail[0]["verdict"],
    "budget_remaining": avail[0]["budget_remaining"],
    "request_spans": len(reqs),
    "merged_events": len(evs),
}))
PY

echo "== async-ckpt smoke (seeded slow disk, SIGKILL mid-commit -> resume from complete generation, non-ckpt_bound) =="
# ISSUE 16 end-to-end: train_lm under a seeded commit throttle (the
# slow-disk fault). The kill leg SIGKILLs right after step 9 — the step-8
# generation's background commit is mid-throttle, so only the step-4
# generation is complete on disk. The resume leg must restore from a
# COMPLETE generation (4, or 8 if the commit squeaked through), run to
# the full step budget, and — because the commit runs off the step path —
# its verdict line must NOT read ckpt_bound even with the throttle still
# armed. `doctor train` on the resumed run's spool exits 0. The LM smoke
# above already pins byte-identical digests across kill/resume at the
# default (async) mode.
env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python - <<'PY' || exit 1
import json, os, re, signal, subprocess, sys, tempfile

root = tempfile.mkdtemp(prefix="tfr_ackpt_smoke_")
data, ck = os.path.join(root, "data"), os.path.join(root, "ckpt")
spool = os.path.join(root, "spool")
env = {**os.environ, "TFR_CKPT_COMMIT_THROTTLE_S": "0.5"}

# kill leg: SIGKILL lands while generation 8's commit sleeps in the
# throttle (the step lines keep flowing — the loop is not waiting on it)
cmd = [sys.executable, "examples/train_lm.py", "--mesh", "dp",
       "--steps", "16", "--save-every", "4", "--data-dir", data,
       "--ckpt-dir", ck, "--digest-out", os.path.join(root, "k.jsonl")]
p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                     stderr=subprocess.STDOUT, text=True, env=env)
for line in p.stdout:
    if line.startswith("lm_step") and \
            json.loads(line.split(" ", 1)[1])["step"] >= 9:
        os.kill(p.pid, signal.SIGKILL)
        break
p.wait()
gens = sorted(n for n in os.listdir(ck) if n.startswith("gen-"))
complete = [g for g in gens
            if os.path.exists(os.path.join(ck, g, "MANIFEST.json"))]
assert complete, (gens, "no complete generation survived the kill")

# resume leg: lighter throttle (commit hides under 4 steps of compute),
# must resume from a complete generation and finish all 16 steps with a
# non-ckpt_bound verdict
env["TFR_CKPT_COMMIT_THROTTLE_S"] = "0.05"
res = subprocess.run(cmd + ["--spool", spool, "--spool-interval", "0.2"],
                     capture_output=True, text=True, env=env, timeout=600)
assert res.returncode == 0, (res.returncode, res.stdout[-2000:], res.stderr[-1000:])
m = re.search(r"resumed at step (\d+)", res.stdout)
assert m and int(m.group(1)) in (4, 8), res.stdout[-1500:]
assert re.search(r"done: 16 steps", res.stdout), res.stdout[-1500:]
v = re.search(r"verdict: (\w+)", res.stdout)
assert v and v.group(1) != "ckpt_bound", res.stdout[-1500:]

# doctor train on the resumed run's spool: exit 0 with a verdict
doc = subprocess.run([sys.executable, "tools/tfrecord_doctor.py", "train",
                      spool, "--stale-after", "3600"],
                     capture_output=True, text=True)
assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
summary = [json.loads(l) for l in doc.stdout.splitlines()
           if l.strip() and json.loads(l).get("event") == "train"][0]
assert summary["verdict"] != "ckpt_bound", summary
print("async-ckpt smoke OK:", json.dumps({
    "resumed_at": int(m.group(1)),
    "complete_generations_after_kill": complete,
    "resume_verdict": v.group(1),
    "doctor_verdict": summary["verdict"],
}))
PY

echo "== trainer-telemetry smoke (train_lm --spool -> doctor train + step-marked trace + MoE counts) =="
# The training flight recorder end-to-end: a short MoE train_lm run spools
# under the trainer role with the flight recorder on. `doctor train` must
# exit 0 with a phase-share verdict, the exported Chrome trace must parse
# with train.step markers, and the in-jit MoE diagnostics must count
# exactly tokens*top_k routed assignments (pinned in-process against the
# same batch) — so the trainer-side observability can't rot.
env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python - <<'PY' || exit 1
import json, os, subprocess, sys, tempfile

root = tempfile.mkdtemp(prefix="tfr_train_smoke_")
spool = os.path.join(root, "spool")
trace_path = os.path.join(root, "trace.json")
env = {**os.environ}
res = subprocess.run(
    [sys.executable, "examples/train_lm.py", "--mesh", "dp", "--moe", "4",
     "--diagnostics", "--steps", "8", "--epochs", "1", "--save-every", "4",
     "--data-dir", os.path.join(root, "data"),
     "--ckpt-dir", os.path.join(root, "ckpt"),
     "--spool", spool, "--spool-interval", "0.2",
     "--trace-out", trace_path],
    capture_output=True, text=True, env=env, timeout=600,
)
assert res.returncode == 0, (res.returncode, res.stdout[-2000:], res.stderr[-1000:])

# the clean exit landed a final trainer snapshot with the train phases
from tpu_tfrecord import fleet
files = [n for n in os.listdir(spool) if n.endswith(fleet.SPOOL_SUFFIX)]
snap = fleet.read_spool(os.path.join(spool, files[0]))
assert snap.final and snap.role == "trainer", (snap.final, snap.role)
assert snap.counters.get("train.steps") == 8, snap.counters
assert "moe.dropped_fraction" in snap.gauges, sorted(snap.gauges)

# doctor train: exit 0, a verdict, phase shares
doc = subprocess.run([sys.executable, "tools/tfrecord_doctor.py", "train",
                      spool, "--stale-after", "3600"],
                     capture_output=True, text=True)
assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
lines = [json.loads(l) for l in doc.stdout.splitlines() if l.strip()]
summary = [l for l in lines if l.get("event") == "train"][0]
assert summary["verdict"] in ("input_bound", "compute_bound", "ckpt_bound")
assert summary["phase_shares"], summary

# the Chrome trace parses and carries one train.step span per step
trace = json.load(open(trace_path))
steps = [e for e in trace["traceEvents"]
         if e.get("name") == "train.step" and e.get("ph") == "X"]
assert len(steps) == 8, len(steps)

# MoE expert counts sum to tokens routed (counts are oracle-pinned in
# tests; here the invariant on a live batch)
import numpy as np, jax, jax.numpy as jnp
from tpu_tfrecord.models import moe
cfg = moe.MoEConfig(d_model=8, d_ff=16, n_experts=4, top_k=2)
params = moe.init_params(jax.random.key(0), cfg)
x = jnp.asarray(np.random.default_rng(0).normal(size=(24, 8)), jnp.float32)
_, _, diag = moe.moe_apply(params, x, cfg, diagnostics=True)
routed = float(np.asarray(diag["expert_tokens"]).sum())
assert routed == 24 * cfg.top_k, routed
print("trainer-telemetry smoke OK:", json.dumps({
    "steps": summary["steps"],
    "verdict": summary["verdict"],
    "step_spans": len(steps),
    "moe_routed": routed,
}))
PY

echo "== HA smoke (2 partitions + warm standby, primary SIGKILL mid-read -> standby serves, byte-identical) =="
# The HA control plane end-to-end, production-shaped: two dispatcher
# PARTITION primaries plus one warm standby, all real subprocesses sharing
# a journal file, two decode workers registered with every partition. The
# primary of the partition that OWNS the dataset's tenant is SIGKILLed
# mid-read; the standby must detect death by ping loss, promote with a
# bumped generation, take over the dead primary's address, and finish the
# epoch byte-identical to a direct local read with ZERO local-read
# fallbacks. `serve-status` over the partition map must exit 0 and report
# the failover — so the failover path can't rot.
env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, os, signal, subprocess, sys, tempfile, time

import tpu_tfrecord.io as tfio
from tpu_tfrecord import service
from tpu_tfrecord.columnar import batch_to_rows
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType

schema = StructType([StructField("id", LongType(), nullable=False),
                     StructField("s", StringType())])
base = tempfile.mkdtemp(prefix="tfr_ha_smoke_")
out = os.path.join(base, "ds")
for s in range(6):
    tfio.write([[i, f"s{i}"] for i in range(s * 30, (s + 1) * 30)],
               schema, out, mode="append" if s else "overwrite")

def epoch_rows(**kw):
    ds = TFRecordDataset(out, batch_size=8, schema=schema,
                         drop_remainder=False, **kw)
    rows = []
    with ds.batches() as it:
        for b in it:
            rows.extend(batch_to_rows(b, ds.schema))
            yield_hook(rows, ds)
    return rows

yield_hook = lambda rows, ds: None
local = epoch_rows()

# which of the two partitions will own this dataset's tenant? (rendezvous
# hashing is over partition INDICES, so the answer predates the addresses)
tenant = service.tenant_digest(
    TFRecordDataset(out, batch_size=8, schema=schema))
owner = service.PartitionMap.parse("h:1,h:2").partition_for(tenant)

env = {**os.environ, "JAX_PLATFORMS": "cpu"}
procs = []
import atexit
def _reap():
    for p in procs:
        if p.poll() is None:
            p.kill()
atexit.register(_reap)

def spawn(*argv):
    p = subprocess.Popen([sys.executable, "-m", "tpu_tfrecord.service", *argv],
                         stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                         text=True, env=env)
    procs.append(p)
    return p, json.loads(p.stdout.readline())

journals = [os.path.join(base, f"journal-{i}.jsonl") for i in range(2)]
prim, addrs = [], []
for i in range(2):
    p, ready = spawn("dispatcher", "--journal", journals[i],
                     "--partition", str(i), "--lease-ttl-s", "10")
    prim.append(p)
    addrs.append(ready["addr"])
standby_p, standby_ready = spawn(
    "dispatcher", "--journal", journals[owner],
    "--standby-of", addrs[owner], "--partition", str(owner),
    "--lease-ttl-s", "10", "--ping-interval", "0.2",
    "--takeover-misses", "3")
groups = list(addrs)
groups[owner] = f"{addrs[owner]}|{standby_ready['addr']}"
spec = ",".join(groups)

for _ in range(2):
    spawn("worker", "--dispatcher", spec)
deadline = time.monotonic() + 60
while time.monotonic() < deadline:
    counts = [len(service.fetch_status(a).get("workers", [])) for a in addrs]
    if counts == [2, 2]:
        break
    time.sleep(0.05)
assert counts == [2, 2], f"workers never registered everywhere: {counts}"

killed = []
def yield_hook(rows, ds):
    if killed or len(rows) < 40:
        return
    os.kill(prim[owner].pid, signal.SIGKILL)  # mid-read, no warning
    prim[owner].wait()
    killed.append(owner)

METRICS.reset()
got = epoch_rows(service=spec, service_deadline_ms=10000)
assert killed, "epoch ended before the kill hook fired"
assert got == local, "post-failover epoch rows differ from direct local read"
assert METRICS.counter("service.fallbacks") == 0, "degraded to local reads"

doc = subprocess.run([sys.executable, "tools/tfrecord_doctor.py",
                      "serve-status", spec],
                     capture_output=True, text=True)
assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
lines = [json.loads(l) for l in doc.stdout.splitlines() if l.strip()]
svc = [l for l in lines if l.get("event") == "service"
       and l.get("partition") == owner][0]
assert svc.get("failed_over") and svc.get("generation", 0) >= 1, svc
ha = [l for l in lines if l.get("event") == "ha"][0]
assert ha["answered"] == 2 and ha["failed_over"] >= 1, ha

for p in procs:
    if p.poll() is None:
        p.terminate()
for p in procs:
    if p.poll() is None:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
print("HA smoke OK:", json.dumps({
    "rows": len(got),
    "owner_partition": owner,
    "failed_over_generation": svc.get("generation"),
    "reconnects": METRICS.counter("service.reconnects"),
}))
PY

echo "== tier-1 tests =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
