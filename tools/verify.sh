#!/usr/bin/env bash
# One-entry-point verification: the fast syntax gate plus the tier-1 test
# command from ROADMAP.md (keep the pytest invocation in sync with it).
# Usage: tools/verify.sh  (from the repo root or anywhere)
set -u
cd "$(dirname "$0")/.."

echo "== syntax gate (compileall) =="
python -m compileall -q tpu_tfrecord || exit 1

echo "== tier-1 tests =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
