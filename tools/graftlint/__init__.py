"""graftlint — the repo's AST + HLO invariant checker.

Six PRs of review-hardening notes were one recurring failure class:
invariants held by convention drift silently until a reviewer catches
them. graftlint turns those conventions into CI-enforced rules over a
shared visitor harness (stdlib ``ast`` only, no new deps):

====================  ======================================================
rule id               invariant
====================  ======================================================
clock-discipline      policy/controller modules (autotune, elastic, retry,
                      stall, fleet, service) never call bare
                      ``time.time/monotonic/sleep`` — decisions go through
                      the injected clock/sleep seams
atomic-write          persisted artifacts are written atomically
                      (``telemetry.atomic_write_bytes`` or stage + replace)
lock-guard            attributes a ``_lock``-contract class mutates under
                      the lock are never mutated outside it
lock-order            the static lock-acquisition graph across the
                      lock-using modules is acyclic (no order inversions)
except-swallow        every broad ``except Exception`` re-raises, bumps a
                      counter, or carries ``# graftlint: swallow(reason)``
vocab-unregistered    metric/span call sites use names registered in
                      tpu_tfrecord/vocabulary.py
vocab-docs            the README vocabulary block matches the registry
hlo-contract          (``--hlo``) every manifest row in hlo_contracts.py
                      compiles with its required collectives present and
                      its forbidden ones absent
====================  ======================================================

Run ``python -m tools.graftlint`` (defaults: ``tpu_tfrecord tools
examples`` against the committed ``tools/graftlint/baseline.txt``), or
``tfrecord_doctor lint``. Findings are ``file:line rule-id message
(fix: hint)``; CI fails only on NEW (non-baselined) findings, and stale
baseline entries warn so grandfathered debt shrinks monotonically.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional

from tools.graftlint.harness import (
    Finding,
    apply_baseline,
    lint_paths,
    load_baseline,
)
from tools.graftlint.rules import default_rules

__all__ = [
    "Finding",
    "run_lint",
    "DEFAULT_PATHS",
    "DEFAULT_BASELINE",
    "REPO_ROOT",
]

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_PATHS = ("tpu_tfrecord", "tools", "examples")
DEFAULT_BASELINE = os.path.join(
    REPO_ROOT, "tools", "graftlint", "baseline.txt"
)


def run_lint(
    paths: Optional[Iterable[str]] = None,
    baseline: Optional[str] = DEFAULT_BASELINE,
    root: str = REPO_ROOT,
    hlo: bool = False,
    rules=None,
) -> Dict:
    """The one entry point the CLI, the doctor subcommand, and the tier-1
    test all call. Returns::

        {"findings": [Finding...],   # new (non-baselined) findings
         "baselined": int,           # findings the baseline absorbed
         "stale_baseline": [key...], # baseline entries with no live match
         "errors": [str...],         # unreadable/unparseable inputs
         "hlo": [dict...]}           # --hlo contract results (may be [])

    Exit-code policy (callers): errors -> 2, findings or failed HLO
    contracts -> 1, else 0; stale baseline entries WARN but do not fail.
    """
    paths = list(paths) if paths else list(DEFAULT_PATHS)
    findings, errors = lint_paths(paths, rules or default_rules(), root)
    baselined = 0
    stale: List[str] = []
    if baseline and os.path.exists(baseline):
        base = load_baseline(baseline)
        new, stale = apply_baseline(findings, base)
        baselined = len(findings) - len(new)
        findings = new
    hlo_results: List[Dict] = []
    if hlo:
        from tools.graftlint import hlo_contracts

        hlo_results = hlo_contracts.check_contracts()
    return {
        "findings": findings,
        "baselined": baselined,
        "stale_baseline": stale,
        "errors": errors,
        "hlo": hlo_results,
    }
