"""CLI: ``python -m tools.graftlint [paths...]``.

Exit 0 = clean (baseline entries absorbed, stale entries at most warn);
1 = new findings (or failed HLO contracts under ``--hlo``);
2 = an input could not be read/parsed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from tools.graftlint import DEFAULT_BASELINE, DEFAULT_PATHS, REPO_ROOT, run_lint


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="AST + HLO invariant checker (see tools/graftlint/__init__.py)",
    )
    ap.add_argument(
        "paths", nargs="*", default=None,
        help=f"files/dirs to lint (default: {' '.join(DEFAULT_PATHS)})",
    )
    ap.add_argument(
        "--baseline", default=DEFAULT_BASELINE, metavar="PATH",
        help="baseline file of grandfathered finding keys "
        "(default: tools/graftlint/baseline.txt)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, including baselined ones",
    )
    ap.add_argument(
        "--json", action="store_true",
        help='emit one JSON document {"events": [...]} instead of text lines',
    )
    ap.add_argument(
        "--hlo", action="store_true",
        help="also compile and check every HLO collective contract "
        "(needs jax + an 8-device CPU platform; slow)",
    )
    ap.add_argument(
        "--vocab-md", action="store_true",
        help="print the generated README vocabulary block and exit",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="write the current finding keys to --baseline (justify each "
        "with a # comment before committing) and exit 0",
    )
    args = ap.parse_args(argv)

    if args.vocab_md:
        sys.path.insert(0, REPO_ROOT)
        from tpu_tfrecord.vocabulary import vocabulary_markdown

        sys.stdout.write(vocabulary_markdown() + "\n")
        return 0

    try:
        result = run_lint(
            paths=args.paths or None,
            # --write-baseline must see EVERY finding: filtering through
            # the existing baseline first would rewrite the file with only
            # the new findings, silently dropping the already-grandfathered
            # keys (and their hand-written justifications) so the very next
            # plain run fails
            baseline=(
                None
                if (args.no_baseline or args.write_baseline)
                else args.baseline
            ),
            hlo=args.hlo,
        )
    except FileNotFoundError as e:
        sys.stderr.write(f"graftlint: {e}\n")
        return 2

    if args.write_baseline:
        lines = ["# graftlint baseline: one key per line, each preceded by"]
        lines.append("# a one-line justification comment. Stale entries warn.")
        for f in result["findings"]:
            lines.append("# TODO: justify this grandfathered finding")
            lines.append(f.key)
        tmp = args.baseline + f".tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines) + "\n")
        os.replace(tmp, args.baseline)
        sys.stderr.write(
            f"graftlint: wrote {len(result['findings'])} keys to "
            f"{args.baseline}\n"
        )
        return 0

    events = [f.to_json() for f in result["findings"]]
    for key in result["stale_baseline"]:
        events.append({"event": "stale_baseline", "key": key})
    for err in result["errors"]:
        events.append({"event": "error", "error": err})
    for entry in result["hlo"]:
        events.append({"event": "hlo_contract", **entry})
    hlo_failed = [e for e in result["hlo"] if not e["ok"] and not e["skipped"]]
    summary = {
        "event": "lint",
        "findings": len(result["findings"]),
        "baselined": result["baselined"],
        "stale_baseline": len(result["stale_baseline"]),
        "errors": len(result["errors"]),
        "hlo_checked": len(result["hlo"]),
        "hlo_failed": len(hlo_failed),
    }
    events.append(summary)

    if args.json:
        sys.stdout.write(json.dumps({"events": events}, sort_keys=True) + "\n")
    else:
        for f in result["findings"]:
            sys.stdout.write(f.format() + "\n")
        for key in result["stale_baseline"]:
            sys.stdout.write(
                f"warning: stale baseline entry (no matching finding; "
                f"delete it): {key!r}\n"
            )
        for err in result["errors"]:
            sys.stdout.write(f"error: {err}\n")
        for entry in result["hlo"]:
            status = (
                "OK" if entry["ok"]
                else "SKIPPED" if entry["skipped"]
                else "FAILED"
            )
            line = f"hlo-contract {entry['name']} {status}"
            if entry["error"]:
                line += f": {entry['error']}"
            sys.stdout.write(line + "\n")
        sys.stdout.write(json.dumps(summary, sort_keys=True) + "\n")

    if result["errors"]:
        return 2
    if result["findings"] or hlo_failed:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
