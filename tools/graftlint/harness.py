"""graftlint's shared machinery: one AST walk per file, rules as
subscribers, pragma suppression, and the committed-baseline protocol.

Findings are ``file:line rule-id message (fix: hint)`` lines. Every
finding also carries a BASELINE KEY that is stable under line-number
drift (rule id + path + a rule-chosen detail such as the enclosing
function), so a committed baseline survives unrelated edits to the same
file. Baseline entries are the keys verbatim, one per line, each
preceded by a ``#`` justification comment; a baseline entry with no
matching finding is STALE and warns (the violation it grandfathers is
gone — delete the entry), while a finding with no baseline entry fails.

Pragmas: ``# graftlint: allow(<rule-id>: <reason>)`` on the flagged line
(or the line just above/below, for multi-line statements) suppresses one
rule at one site; the exception-audit rule additionally honors its own
``# graftlint: swallow(<reason>)`` spelling as documented compliance
rather than suppression. Reasons are mandatory — a bare pragma is itself
a finding.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from collections import Counter
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Finding",
    "FileContext",
    "Rule",
    "RepoContext",
    "walk_file",
    "lint_paths",
    "load_baseline",
    "apply_baseline",
    "iter_python_files",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    hint: str = ""
    detail: str = ""  # rule-chosen stable fragment of the baseline key

    @property
    def key(self) -> str:
        """Baseline key: line-number-free so the baseline survives edits
        elsewhere in the file."""
        return f"{self.rule}\t{self.path}\t{self.detail or self.message}"

    def format(self) -> str:
        out = f"{self.path}:{self.line} {self.rule} {self.message}"
        if self.hint:
            out += f" (fix: {self.hint})"
        return out

    def to_json(self) -> Dict:
        return {
            "event": "finding",
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "hint": self.hint,
            "key": self.key,
        }


# greedy body match: reasons may themselves contain parentheses
# ("counted in _kill (cache.populate_errors)") — the pragma runs to the
# LAST closing paren on the line
_PRAGMA_RE = re.compile(r"#\s*graftlint:\s*([\w-]+)\((.*)\)")


class FileContext:
    """One parsed source file plus the line-level pragma index."""

    def __init__(self, path: str, rel_path: str, source: str):
        self.path = path
        self.rel = rel_path.replace(os.sep, "/")
        self.name = os.path.basename(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> [(action, reason)]
        self.pragmas: Dict[int, List[Tuple[str, str]]] = {}
        for i, text in enumerate(self.lines, 1):
            if "graftlint" not in text:
                continue
            for m in _PRAGMA_RE.finditer(text):
                self.pragmas.setdefault(i, []).append(
                    (m.group(1), m.group(2).strip())
                )

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def pragma(self, lineno: int, action: str) -> Optional[str]:
        """The reason string of an ``action`` pragma on ``lineno`` or its
        immediate neighbors (multi-line statements put the comment where
        it fits), or None. An empty reason returns "" — callers treat
        that as its own violation."""
        for ln in (lineno, lineno - 1, lineno + 1):
            for act, reason in self.pragmas.get(ln, ()):
                if act == action:
                    return reason
        return None

    def allow_pragma(self, lineno: int, rule_id: str) -> Optional[str]:
        """``# graftlint: allow(<rule-id>: <reason>)`` targeting
        ``rule_id`` near ``lineno`` — the generic suppression every rule
        honors."""
        for ln in (lineno, lineno - 1, lineno + 1):
            for act, reason in self.pragmas.get(ln, ()):
                if act != "allow":
                    continue
                head, _, rest = reason.partition(":")
                if head.strip() == rule_id:
                    return rest.strip()
        return None


class RepoContext:
    """Cross-file state handed to ``Rule.finish``: the repo root and the
    README path for the docs-drift rule."""

    def __init__(self, root: str, readme: Optional[str] = None):
        self.root = root
        self.readme = readme or os.path.join(root, "README.md")


class Rule:
    """One invariant. Subclasses set ``id``/``hint``, implement ``visit``
    (called for every AST node with the walker's lexical context) and/or
    the ``finish_file``/``finish`` hooks, and emit via ``self.emit``.

    Rules never filter pragmas or baselines themselves (except the
    exception-audit's ``swallow`` spelling, which is COMPLIANCE, not
    suppression) — the harness applies ``allow`` pragmas and the baseline
    uniformly after collection."""

    id: str = ""
    hint: str = ""

    def __init__(self) -> None:
        self.findings: List[Finding] = []

    # -- hooks ---------------------------------------------------------------

    def start_file(self, ctx: FileContext) -> None:
        pass

    def visit(self, node: ast.AST, walker: "Walker") -> None:
        pass

    def finish_file(self, ctx: FileContext) -> None:
        pass

    def finish(self, repo: RepoContext) -> None:
        pass

    # -- emission ------------------------------------------------------------

    def emit(
        self,
        ctx: FileContext,
        lineno: int,
        message: str,
        detail: str = "",
        hint: Optional[str] = None,
    ) -> None:
        self.findings.append(
            Finding(
                rule=self.id,
                path=ctx.rel,
                line=lineno,
                message=message,
                hint=self.hint if hint is None else hint,
                detail=detail,
            )
        )


class Walker:
    """One recursive pass over a file's AST, tracking the lexical context
    rules need: enclosing class/function stacks and the with-held lock
    stack. Rules read ``walker.class_stack``/``func_stack``/
    ``lock_stack``/``ctx`` during ``visit``."""

    #: with-items recognized as lock acquisitions: ``self.<x>`` or a bare
    #: name whose identifier contains "lock" (``_lock``, ``_ds_lock``,
    #: module-global ``_lock``).
    @staticmethod
    def lock_ident(expr: ast.AST) -> Optional[Tuple[str, str]]:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and "lock" in expr.attr.lower()
        ):
            return ("self", expr.attr)
        if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
            return ("global", expr.id)
        return None

    def __init__(self, ctx: FileContext, rules: List[Rule]):
        self.ctx = ctx
        self.rules = rules
        self.class_stack: List[ast.ClassDef] = []
        self.func_stack: List[ast.AST] = []  # FunctionDef/AsyncFunctionDef/Lambda
        self.lock_stack: List[Tuple[str, str]] = []

    @property
    def qualname(self) -> str:
        parts = [c.name for c in self.class_stack] + [
            getattr(f, "name", "<lambda>") for f in self.func_stack
        ]
        return ".".join(parts) or "<module>"

    def holds(self, ident: Tuple[str, str]) -> bool:
        return ident in self.lock_stack

    def walk(self, node: ast.AST) -> None:
        for rule in self.rules:
            rule.visit(node, self)
        if isinstance(node, ast.ClassDef):
            self.class_stack.append(node)
            # a nested class's methods are not the outer function's body
            saved_funcs, self.func_stack = self.func_stack, []
            saved_locks, self.lock_stack = self.lock_stack, []
            for child in ast.iter_child_nodes(node):
                self.walk(child)
            self.func_stack = saved_funcs
            self.lock_stack = saved_locks
            self.class_stack.pop()
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            self.func_stack.append(node)
            for child in ast.iter_child_nodes(node):
                self.walk(child)
            self.func_stack.pop()
        elif isinstance(node, ast.With):
            acquired: List[Tuple[str, str]] = []
            for item in node.items:
                ident = self.lock_ident(item.context_expr)
                if ident is not None:
                    acquired.append(ident)
                    self.lock_stack.append(ident)
                self.walk(item.context_expr)
                if item.optional_vars is not None:
                    self.walk(item.optional_vars)
            for child in node.body:
                self.walk(child)
            for _ in acquired:
                self.lock_stack.pop()
        else:
            for child in ast.iter_child_nodes(node):
                self.walk(child)


def walk_file(ctx: FileContext, rules: List[Rule]) -> None:
    for rule in rules:
        rule.start_file(ctx)
    Walker(ctx, rules).walk(ctx.tree)
    for rule in rules:
        rule.finish_file(ctx)


def iter_python_files(paths: Iterable[str], root: str) -> List[Tuple[str, str]]:
    """(abs_path, rel_path) for every .py under ``paths`` (files or dirs),
    sorted, __pycache__ skipped. Raises FileNotFoundError for a missing
    path — an unreadable target is exit 2, not an empty clean run."""
    out: List[Tuple[str, str]] = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            out.append((ap, os.path.relpath(ap, root)))
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(
                    d for d in dirnames if d != "__pycache__"
                )
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        fp = os.path.join(dirpath, f)
                        out.append((fp, os.path.relpath(fp, root)))
        else:
            raise FileNotFoundError(f"no such file or directory: {p}")
    return sorted(set(out))


def lint_paths(
    paths: Iterable[str],
    rules: List[Rule],
    root: str,
    repo: Optional[RepoContext] = None,
) -> Tuple[List[Finding], List[str]]:
    """Run ``rules`` over every Python file under ``paths``. Returns
    (findings after pragma suppression, unreadable-file errors). The
    baseline is NOT applied here — callers own that policy (the CLI and
    the doctor apply it; tests often want the raw findings)."""
    repo = repo or RepoContext(root)
    errors: List[str] = []
    contexts: List[FileContext] = []
    for ap, rel in iter_python_files(paths, root):
        try:
            with open(ap, "r", encoding="utf-8") as fh:
                source = fh.read()
            ctx = FileContext(ap, rel, source)
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{rel}: {e}")
            continue
        contexts.append(ctx)
        walk_file(ctx, rules)
    for rule in rules:
        rule.finish(repo)
    ctx_by_rel = {c.rel: c for c in contexts}
    findings: List[Finding] = []
    for rule in rules:
        for f in rule.findings:
            ctx = ctx_by_rel.get(f.path)
            if ctx is not None:
                reason = ctx.allow_pragma(f.line, f.rule)
                if reason:
                    continue
                if reason == "":  # pragma present but reasonless
                    f = dataclasses.replace(
                        f,
                        message=f.message
                        + " [allow pragma present but gives no reason]",
                    )
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, errors


# -- baseline ----------------------------------------------------------------


def load_baseline(path: str) -> Counter:
    """The baseline as a multiset of finding keys. Lines: ``#`` comments
    (the mandatory justifications) and blanks are skipped; anything else
    is one key, verbatim."""
    keys: Counter = Counter()
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.rstrip("\n")
            if not line.strip() or line.lstrip().startswith("#"):
                continue
            keys[line] += 1
    return keys


def apply_baseline(
    findings: List[Finding], baseline: Counter
) -> Tuple[List[Finding], List[str]]:
    """(new findings not covered by the baseline, stale baseline keys with
    no live finding). Multiset semantics: N identical findings need N
    baseline entries."""
    remaining = Counter(baseline)
    new: List[Finding] = []
    for f in findings:
        if remaining.get(f.key, 0) > 0:
            remaining[f.key] -= 1
        else:
            new.append(f)
    stale = sorted(k for k, n in remaining.items() if n > 0 for _ in range(n))
    return new, stale
