"""The rule classes: one per invariant the codebase previously held by
convention (see tools/graftlint/__init__ for the inventory). Each rule is
a subscriber on the shared harness walk; findings carry a fix hint and a
line-drift-stable baseline key.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.harness import FileContext, RepoContext, Rule, Walker

__all__ = [
    "ClockDisciplineRule",
    "AtomicWriteRule",
    "LockGuardRule",
    "LockOrderRule",
    "ExceptSwallowRule",
    "VocabularyRule",
    "default_rules",
]


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover — unparse is total on parsed trees  # graftlint: swallow(unparse guard for exotic nodes; placeholder returned)
        return "<expr>"


# ---------------------------------------------------------------------------
# clock-discipline
# ---------------------------------------------------------------------------


class ClockDisciplineRule(Rule):
    """Policy/controller modules must read time and sleep through their
    injected seams (``policy.clock``/``policy.sleep``, ctor ``clock=``
    params): a bare ``time.time()``/``time.monotonic()``/``time.sleep()``
    in a decision path makes hysteresis/cooldown/lease logic untestable
    and non-deterministic. Referencing ``time.monotonic`` as a DEFAULT
    (``clock: Callable = time.monotonic``) is the seam itself and is not
    a call, so only calls are flagged."""

    id = "clock-discipline"
    hint = "route through the injected clock/sleep seam (ctor/policy argument)"

    #: The policy modules (decision logic gated on wall time). io/wire
    #: timing instrumentation (perf_counter spans) is out of scope.
    MODULES = {
        "autotune.py", "elastic.py", "retry.py", "stall.py", "fleet.py",
        "service.py", "serving.py",
    }
    CALLS = {"time", "monotonic", "sleep"}

    def visit(self, node: ast.AST, walker: Walker) -> None:
        if walker.ctx.name not in self.MODULES:
            return
        if not isinstance(node, ast.Call):
            return
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "time"
            and fn.attr in self.CALLS
        ):
            self.emit(
                walker.ctx,
                node.lineno,
                f"bare time.{fn.attr}() in policy module "
                f"{walker.ctx.name} ({walker.qualname})",
                detail=f"time.{fn.attr}@{walker.qualname}",
            )


# ---------------------------------------------------------------------------
# atomic-write
# ---------------------------------------------------------------------------


class AtomicWriteRule(Rule):
    """Persisted artifacts (spools, cache entries, checkpoints, traces,
    journals) must land atomically: ``telemetry.atomic_write_bytes`` or
    stage-to-tmp + ``os.replace``. A bare write-mode ``open(p, "w")`` on
    a final path tears on crash and the reader (aggregator, Perfetto,
    resume) chokes on the stump. Compliant shapes recognized statically:
    the enclosing function also renames (stage-then-replace), or the path
    expression names a tmp/staging location (the stage file of such a
    pattern), or the enclosing function commits a MANIFEST afterwards via
    one of the shared durable-write helpers (the manifest-last sharded
    generation idiom: staged shard files are made visible-as-a-set by a
    later ``checkpoint.durable_write``/``atomic_write_bytes`` of the
    manifest, so readers only ever observe complete generations). The
    helper call must come AFTER the staged write — a manifest committed
    first covers nothing and stays flagged.

    Append-ONLY opens (``"a"``/``"ab"`` with no ``w``/``x``) get their
    own idiom: ``checkpoint.durable_append``'s fsync-before-return shape.
    An append never truncates — a crash tears at most the unfsynced
    tail, which a newest-consistent-prefix reader (the dispatcher
    journal replay) absorbs by design — so an append-only open whose
    enclosing scope also calls ``os.fsync`` is compliant. An append
    WITHOUT the fsync still tears silently across a host crash and
    stays flagged."""

    id = "atomic-write"
    hint = (
        "write via telemetry.atomic_write_bytes or checkpoint.durable_write, "
        "stage to a tmp path and os.replace into place, commit a "
        "manifest LAST via one of those helpers, or (append-only logs) "
        "go through checkpoint.durable_append's fsync-before-return shape"
    )

    _STAGED_PATH_MARKERS = ("tmp", "staging", "partial", "scratch")
    _RENAMES = {"replace", "rename", "renames"}
    _COMMIT_HELPERS = {"atomic_write_bytes", "durable_write"}

    def visit(self, node: ast.AST, walker: Walker) -> None:
        if not isinstance(node, ast.Call):
            return
        fn = node.func
        if not (isinstance(fn, ast.Name) and fn.id == "open"):
            return
        if len(node.args) < 2:
            return  # mode defaults to "r"
        mode = node.args[1]
        if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
            return
        # any truncating/creating mode counts — "w+" tears the destination
        # exactly like "w" ("r+" has no w/a/x and falls through)
        if not ({"w", "a", "x"} & set(mode.value)):
            return
        path_src = _unparse(node.args[0]).lower()
        if any(m in path_src for m in self._STAGED_PATH_MARKERS):
            return  # the stage file of a stage-then-replace pattern
        scope: ast.AST = (
            walker.func_stack[-1] if walker.func_stack else walker.ctx.tree
        )
        append_only = "a" in mode.value and not ({"w", "x"} & set(mode.value))
        if append_only and self._scope_fsyncs(scope):
            return  # the durable-append idiom (fsync before return)
        if self._scope_renames(scope):
            return
        if self._scope_commits_manifest_after(scope, node.lineno):
            return
        self.emit(
            walker.ctx,
            node.lineno,
            f"non-atomic write-mode open({_unparse(node.args[0])}, "
            f"{mode.value!r}) in {walker.qualname}",
            detail=f"open@{walker.qualname}:{_unparse(node.args[0])}",
        )

    @staticmethod
    def _scope_fsyncs(scope: ast.AST) -> bool:
        """An ``os.fsync(...)`` anywhere in the scope — paired with an
        append-only open this is the durable-append shape (the bytes are
        on the platter before the writer reports success)."""
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Call):
                f = sub.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "fsync"
                    and _unparse(f.value) == "os"
                ):
                    return True
        return False

    def _scope_renames(self, scope: ast.AST) -> bool:
        """A rename call that plausibly lands a staged file: ``os.replace``/
        ``os.rename`` or a filesystem object's ``.rename`` (``fs``,
        ``self.fs``, ``_fs.filesystem_for(...)``). A bare ``str.replace``
        on some unrelated variable must NOT exempt the write."""
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Attribute) and f.attr in self._RENAMES:
                    recv = _unparse(f.value)
                    if recv == "os" or "fs" in recv.lower():
                        return True
        return False

    def _scope_commits_manifest_after(self, scope: ast.AST, lineno: int) -> bool:
        """The manifest-last idiom: the scope calls one of the shared
        durable-write commit helpers AFTER this write (by line), so the
        staged file only becomes load-bearing once the manifest lands
        atomically. A helper call BEFORE the write is manifest-first —
        it commits nothing about the bytes written later, so it must not
        exempt them."""
        for sub in ast.walk(scope):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if name in self._COMMIT_HELPERS and sub.lineno > lineno:
                return True
        return False


# ---------------------------------------------------------------------------
# lock-guard
# ---------------------------------------------------------------------------

#: Method calls that mutate common containers in place.
_MUTATORS = {
    "append", "appendleft", "add", "pop", "popleft", "popitem", "update",
    "clear", "remove", "discard", "extend", "insert", "setdefault",
}


class LockGuardRule(Rule):
    """For classes declaring the ``_lock`` contract (``self._lock =
    threading.Lock()`` in ``__init__``), every attribute the class
    mutates under ``with self._lock`` is a GUARDED attribute — and any
    mutation of it outside the lock (outside ``__init__``, which is
    happens-before publication, and outside ``*_locked`` helpers, the
    repo's called-with-lock-held convention) is a race waiting for a
    second thread."""

    id = "lock-guard"
    hint = (
        "mutate under `with self._lock` (or move into a *_locked helper "
        "called with the lock held)"
    )

    def start_file(self, ctx: FileContext) -> None:
        # class qualname -> {attr: [(under_lock, in_init_or_locked, lineno, qual)]}
        self._mutations: Dict[str, List[Tuple[str, bool, bool, int, str]]] = {}
        self._declares_lock: Set[str] = set()

    def _class_key(self, walker: Walker) -> Optional[str]:
        if not walker.class_stack:
            return None
        return ".".join(c.name for c in walker.class_stack)

    @staticmethod
    def _exempt(walker: Walker) -> bool:
        """Mutations in __init__ (pre-publication) or *_locked helpers
        (called with the lock held by convention) are compliant."""
        for f in walker.func_stack:
            name = getattr(f, "name", "")
            if name == "__init__" or name.endswith("_locked"):
                return True
        return False

    def _record(self, walker: Walker, attr: str, lineno: int) -> None:
        key = self._class_key(walker)
        if key is None or not walker.func_stack:
            return
        self._mutations.setdefault(key, []).append(
            (
                attr,
                ("self", "_lock") in walker.lock_stack,
                self._exempt(walker),
                lineno,
                walker.qualname,
            )
        )

    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def visit(self, node: ast.AST, walker: Walker) -> None:
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
                if isinstance(node, (ast.AugAssign, ast.AnnAssign))
                else node.targets
            )
            for t in targets:
                attr = self._self_attr(t)
                if attr == "_lock" and isinstance(node, ast.Assign):
                    key = self._class_key(walker)
                    if key is not None:
                        self._declares_lock.add(key)
                    continue
                if attr is not None:
                    self._record(walker, attr, node.lineno)
                    continue
                # self.X[...] = v / del self.X[...]
                if isinstance(t, ast.Subscript):
                    attr = self._self_attr(t.value)
                    if attr is not None:
                        self._record(walker, attr, node.lineno)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                attr = self._self_attr(f.value)
                if attr is not None:
                    self._record(walker, attr, node.lineno)

    def finish_file(self, ctx: FileContext) -> None:
        for cls, muts in self._mutations.items():
            if cls not in self._declares_lock:
                continue
            guarded = {
                attr for attr, under, _ex, _ln, _q in muts if under
            }
            for attr, under, exempt, lineno, qual in muts:
                if attr in guarded and not under and not exempt:
                    self.emit(
                        ctx,
                        lineno,
                        f"{cls}.{attr} is mutated under self._lock "
                        f"elsewhere but written WITHOUT it in {qual}",
                        detail=f"{cls}.{attr}@{qual}",
                    )


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------


class LockOrderRule(Rule):
    """Static lock-acquisition graph over every scanned module: a lexical
    ``with lockB`` inside ``with lockA`` adds edge A→B. Any CYCLE in the
    resulting digraph is a potential lock-order inversion — two threads
    entering the cycle from different nodes deadlock. Lock identity is
    ``module.Class.attr`` for ``self.*lock*`` attributes and
    ``module.name`` for module-level locks (instances of one class are
    conflated — conservative, the direction a deadlock checker must
    err)."""

    id = "lock-order"
    hint = "acquire these locks in one global order (or merge them)"

    def __init__(self) -> None:
        super().__init__()
        # edge -> first (path, line) observed
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def _lock_id(self, walker: Walker, ident: Tuple[str, str]) -> str:
        mod = os.path.splitext(walker.ctx.name)[0]
        kind, name = ident
        if kind == "self" and walker.class_stack:
            return f"{mod}.{walker.class_stack[-1].name}.{name}"
        return f"{mod}.{name}"

    def visit(self, node: ast.AST, walker: Walker) -> None:
        if not isinstance(node, ast.With):
            return
        # visit() runs before the walker pushes this With's own locks, so
        # a multi-item `with a_lock, b_lock:` threads its items manually:
        # item N is acquired while items 0..N-1 (and every enclosing
        # lock) are held
        held = [self._lock_id(walker, h) for h in walker.lock_stack]
        for item in node.items:
            ident = Walker.lock_ident(item.context_expr)
            if ident is None:
                continue
            inner = self._lock_id(walker, ident)
            for outer in held:
                # outer == inner is KEPT: `with self.X: with self.X:` is
                # the same instance by construction (both spell `self`) —
                # a guaranteed self-deadlock on a non-reentrant Lock,
                # reported via the self-loop branch of the SCC scan
                self.edges.setdefault(
                    (outer, inner), (walker.ctx.rel, node.lineno)
                )
            held.append(inner)

    def finish(self, repo: RepoContext) -> None:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        from tools.graftlint.harness import Finding

        for cycle in self._cycles(graph):
            a, b = cycle[0], cycle[1 % len(cycle)]
            path, line = self.edges.get((a, b), ("<multiple>", 0))
            ring = " -> ".join(cycle + [cycle[0]])
            self.findings.append(
                Finding(
                    rule=self.id,
                    path=path,
                    line=line,
                    message=f"lock-order cycle (potential deadlock): {ring}",
                    hint=self.hint,
                    detail="cycle:" + "|".join(sorted(cycle)),
                )
            )

    @staticmethod
    def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
        """Strongly-connected components of size > 1 (plus self-loops):
        each is reported once as a sorted node ring."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in graph.get(v, ()):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1 or v in graph.get(v, ()):
                    out.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        return out


# ---------------------------------------------------------------------------
# except-swallow
# ---------------------------------------------------------------------------


class ExceptSwallowRule(Rule):
    """Every ``except Exception``/``except BaseException`` must do one of:
    re-raise, bump a counter (preferably an ``*.errors``/``*_errors``
    family — the swallow stays observable on the pulse/doctor), or carry
    an explicit ``# graftlint: swallow(<reason>)`` pragma documenting why
    silence is correct. A reasonless pragma is itself a finding."""

    id = "except-swallow"
    hint = (
        "re-raise, bump an *.errors counter, or annotate "
        "`# graftlint: swallow(<why silence is correct>)`"
    )

    _BROAD = {"Exception", "BaseException"}

    def start_file(self, ctx: FileContext) -> None:
        self._ordinals: Dict[str, int] = {}

    def _is_broad(self, type_node: Optional[ast.AST]) -> bool:
        if type_node is None:
            return True  # bare `except:` is the broadest spelling of all
        if isinstance(type_node, ast.Name):
            return type_node.id in self._BROAD
        if isinstance(type_node, ast.Tuple):
            return any(self._is_broad(e) for e in type_node.elts)
        return False

    @classmethod
    def _handler_complies(cls, handler: ast.ExceptHandler) -> bool:
        """A ``raise`` reachable on the except path, or a counter bump on a
        metrics registry. Nested function bodies do NOT count (a raise in a
        closure never fires on this path), and neither does ``list.count``/
        ``str.count`` — the receiver must look like a registry."""
        for sub in cls._walk_no_defs(handler.body):
            if isinstance(sub, ast.Raise):
                return True
            if isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Attribute) and f.attr == "count":
                    recv = _unparse(f.value).rsplit(".", 1)[-1]
                    if recv in ("METRICS", "metrics"):
                        return True
        return False

    @staticmethod
    def _walk_no_defs(body):
        stack = list(body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue  # a nested def's body never runs on this path
            stack.extend(ast.iter_child_nodes(node))

    def visit(self, node: ast.AST, walker: Walker) -> None:
        if not isinstance(node, ast.ExceptHandler):
            return
        if not self._is_broad(node.type):
            return
        ctx = walker.ctx
        reason = ctx.pragma(node.lineno, "swallow")
        if reason:
            return
        if self._handler_complies(node):
            return
        qual = walker.qualname
        n = self._ordinals.get(qual, 0)
        self._ordinals[qual] = n + 1
        spelled = _unparse(node.type) if node.type is not None else "<bare>"
        if reason == "":
            msg = (
                f"except {spelled} carries a swallow pragma with no reason "
                f"in {qual}"
            )
        else:
            msg = (
                f"except {spelled} swallows without re-raise, counter, or "
                f"pragma in {qual}"
            )
        self.emit(ctx, node.lineno, msg, detail=f"except@{qual}#{n}")


# ---------------------------------------------------------------------------
# vocabulary
# ---------------------------------------------------------------------------


class VocabularyRule(Rule):
    """Call sites must use REGISTERED names (tpu_tfrecord/vocabulary.py),
    and the README's generated vocabulary block must match the registry —
    drift in either direction fails.

    Literal first arguments are checked against the right kind; f-strings
    are checked by their leading constant against the registered dynamic
    prefixes; everything else (variables, ``X + ".errors"``) is
    statically unknowable and skipped — the dynamic spellings in tree all
    ride registered prefixes/suffixes by construction."""

    id = "vocab-unregistered"
    DOCS_ID = "vocab-docs"
    hint = (
        "register the name in tpu_tfrecord/vocabulary.py and refresh the "
        "README block (python -m tools.graftlint --vocab-md)"
    )

    _METHOD_KINDS = {
        "count": "counter",
        "counter": "counter",
        "add": "stage",
        "observe": "stage",
        "stage": "stage",
        "timed": "stage",
        "gauge": "gauge",
        "gauge_value": "gauge",
    }
    _SPAN_FUNCS = {"span", "instant", "record_span"}
    _SPAN_RECEIVERS = {"telemetry", "RECORDER"}

    def __init__(self, vocab=None) -> None:
        super().__init__()
        if vocab is None:
            from tpu_tfrecord import vocabulary as vocab
        self.vocab = vocab

    def _call_kind(self, node: ast.Call) -> Optional[str]:
        fn = node.func
        if isinstance(fn, ast.Name):
            if fn.id == "timed":
                return "stage"
            if fn.id in self._SPAN_FUNCS:
                return "span"
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        recv = _unparse(fn.value)
        if fn.attr in self._SPAN_FUNCS:
            tail = recv.rsplit(".", 1)[-1]
            return "span" if tail in self._SPAN_RECEIVERS else None
        kind = self._METHOD_KINDS.get(fn.attr)
        if kind is None:
            return None
        tail = recv.rsplit(".", 1)[-1]
        # only metrics registries: `METRICS.count`, `self.metrics.add`,
        # `metrics.gauge` — never `seen.add` / `conns.discard`
        return kind if tail in ("METRICS", "metrics") else None

    def visit(self, node: ast.AST, walker: Walker) -> None:
        if not isinstance(node, ast.Call) or not node.args:
            return
        kind = self._call_kind(node)
        if kind is None:
            return
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
            if not self.vocab.is_registered(name, kind):
                self.emit(
                    walker.ctx,
                    node.lineno,
                    f"unregistered {kind} name {name!r} at "
                    f"{walker.qualname}",
                    detail=f"{kind}:{name}",
                )
        elif isinstance(arg, ast.JoinedStr) and arg.values:
            head = arg.values[0]
            if isinstance(head, ast.Constant) and isinstance(head.value, str):
                prefix = head.value
                dyn = self.vocab.DYNAMIC_PREFIXES.get(kind, {})
                if not any(prefix.startswith(p) for p in dyn):
                    self.emit(
                        walker.ctx,
                        node.lineno,
                        f"dynamic {kind} name f-string {prefix!r}... has no "
                        f"registered dynamic prefix ({walker.qualname})",
                        detail=f"{kind}:f:{prefix}",
                    )

    def finish(self, repo: RepoContext) -> None:
        from tools.graftlint.harness import Finding

        v = self.vocab
        try:
            with open(repo.readme, "r", encoding="utf-8") as fh:
                readme = fh.read()
        except OSError as e:
            self.findings.append(
                Finding(
                    rule=self.DOCS_ID, path="README.md", line=1,
                    message=f"README unreadable: {e}", hint=self.hint,
                    detail="readme-unreadable",
                )
            )
            return
        begin, end = v.VOCABULARY_BEGIN, v.VOCABULARY_END
        i, j = readme.find(begin), readme.find(end)
        if i < 0 or j < 0 or j < i:
            self.findings.append(
                Finding(
                    rule=self.DOCS_ID, path="README.md", line=1,
                    message="README has no generated vocabulary block "
                    f"({begin.split(' ')[0]}...)",
                    hint=self.hint, detail="readme-block-missing",
                )
            )
            return
        block = readme[i : j + len(end)]
        want = v.vocabulary_markdown()
        if block.strip() != want.strip():
            line = readme.count("\n", 0, i) + 1
            # name the first drifted entry so the finding is actionable
            got_lines = set(block.splitlines())
            missing = [
                ln for ln in want.splitlines() if ln not in got_lines
            ]
            first = missing[0] if missing else "(entries removed)"
            self.findings.append(
                Finding(
                    rule=self.DOCS_ID, path="README.md", line=line,
                    message="README vocabulary block is stale vs "
                    f"tpu_tfrecord/vocabulary.py (first drift: {first!r})",
                    hint=self.hint, detail="readme-block-stale",
                )
            )


def default_rules() -> List[Rule]:
    return [
        ClockDisciplineRule(),
        AtomicWriteRule(),
        LockGuardRule(),
        LockOrderRule(),
        ExceptSwallowRule(),
        VocabularyRule(),
    ]
