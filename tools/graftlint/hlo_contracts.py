"""The HLO collective contracts as DATA: one declarative manifest of
jitted entrypoint → required/forbidden collectives, checked by one driver.

The model-parallel layer's contracts are comms contracts — "the pipeline
feed ring moves microbatches by collective-permute and never gathers the
stream", "EP MoE dispatch is an all-to-all" (the GSPMD sharding
discipline, PAPERS.md) — and before this manifest each pin lived as an
inline ``contains=/absent=`` pair duplicated across four test files. Here
the contract lives ONCE: tests and the ``python -m tools.graftlint
--hlo`` driver both read this table, so a new schedule variant gets its
pin by adding a row, and the diagnostics-on/off twins can't drift from
each other.

Builders construct the exact (fn, args) the historical tests compiled
(same meshes, shapes, and sharding layouts), and the driver compiles
through ``tests/hlo_util.compiled()`` — the one compiled-handle owner —
so the text being grepped is the post-SPMD-partitioning program the
backend will actually run. Everything jax-flavored imports lazily: the
static lint rules never pay for (or require) a backend.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "HloContract",
    "CONTRACTS",
    "get",
    "build",
    "verify",
    "check_contracts",
    "ensure_hlo_util",
]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class HloContract:
    """One jitted entrypoint's collective contract.

    ``contains``: collectives that MUST appear in the compiled HLO;
    ``absent``: collectives that must NOT. ``builder`` returns (fn, args)
    ready to compile — the canonical construction of the entrypoint at
    pin scale (8-device CPU mesh)."""

    name: str
    entrypoint: str  # dotted, human-facing: which jitted fn this pins
    contains: Tuple[str, ...]
    absent: Tuple[str, ...]
    builder: Callable[[], Tuple[Callable, Tuple]]
    diagnostics: bool = False
    note: str = ""


def ensure_hlo_util():
    """Import tests/hlo_util (the one compiled-handle owner) from the
    repo's tests directory, forcing the 8-device CPU platform first when
    no backend exists yet (the tests' conftest does the same)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    tests_dir = os.path.join(_REPO_ROOT, "tests")
    if tests_dir not in sys.path:
        sys.path.insert(0, tests_dir)
    import hlo_util

    return hlo_util


# ---------------------------------------------------------------------------
# builders (lazy jax imports; constructions mirror the historical pins)
# ---------------------------------------------------------------------------


def _pipeline_fixture(n_stages: int, d: int = 8, seed: int = 0):
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(seed)
    params = {
        "w": jnp.asarray(rng.normal(size=(n_stages, d, d)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.normal(size=(n_stages, d)) * 0.1, jnp.float32),
    }

    def stage_fn(p, x):
        return jax.nn.gelu(x @ p["w"] + p["b"])

    return params, stage_fn


def _build_pipeline_feed_ring():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_tfrecord.models import pipeline
    from tpu_tfrecord.tpu import create_mesh

    mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
    params, stage_fn = _pipeline_fixture(4)
    xs = jnp.zeros((4, 2, 8), jnp.float32)
    p_sh = jax.device_put(params, NamedSharding(mesh, P("pipe")))
    xs_sh = jax.device_put(
        xs, pipeline.microbatch_sharding(mesh, "pipe", ndim=xs)
    )
    fn = jax.jit(lambda p, x: pipeline.pipeline_apply(stage_fn, p, x, mesh))
    return fn, (p_sh, xs_sh)


def _build_pipeline_feed_ring_dp():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_tfrecord.models import pipeline
    from tpu_tfrecord.tpu import create_mesh

    mesh = create_mesh({"pipe": 4, "data": 2})
    params, stage_fn = _pipeline_fixture(4)
    xs = jnp.zeros((8, 4, 8), jnp.float32)
    p_sh = jax.device_put(params, NamedSharding(mesh, P("pipe")))
    xs_sh = jax.device_put(
        xs,
        pipeline.microbatch_sharding(mesh, ndim=xs, batch_spec=P("data")),
    )
    fn = jax.jit(
        lambda p, x: pipeline.pipeline_apply(
            stage_fn, p, x, mesh, batch_spec=P("data")
        )
    )
    return fn, (p_sh, xs_sh)


def _build_pipeline_diagnostics():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_tfrecord.models import pipeline
    from tpu_tfrecord.tpu import create_mesh

    mesh = create_mesh({"pipe": 4, "data": 2})
    params = {
        "w": jnp.asarray(
            np.random.default_rng(0).normal(size=(4, 8, 8)) * 0.1, jnp.float32
        )
    }

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    xs = jnp.zeros((8, 4, 8), jnp.float32)
    xs_sh = jax.device_put(xs, pipeline.microbatch_sharding(mesh, ndim=xs))
    fn = jax.jit(
        lambda p, x: pipeline.pipeline_apply(
            stage_fn, p, x, mesh, diagnostics=True
        )[0]
    )
    return fn, (params, xs_sh)


def _build_pipeline_interleaved():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_tfrecord.models import pipeline
    from tpu_tfrecord.tpu import create_mesh

    import numpy as np

    mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
    rng = np.random.default_rng(0)
    # [S, V, ...] stage stack: device d owns the 2 round-robin chunks
    # d and d+4 of the 8 virtual stages
    params = {
        "w": jnp.asarray(rng.normal(size=(4, 2, 8, 8)) * 0.5, jnp.float32),
    }

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    xs = jnp.zeros((8, 2, 8), jnp.float32)
    p_sh = jax.device_put(params, NamedSharding(mesh, P("pipe")))
    xs_sh = jax.device_put(xs, pipeline.microbatch_sharding(mesh, "pipe", xs))
    fn = jax.jit(
        lambda p, x: pipeline.pipeline_apply(stage_fn, p, x, mesh, n_virtual=2)
    )
    return fn, (p_sh, xs_sh)


def _build_pipeline_stream_step():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_tfrecord.models import pipeline
    from tpu_tfrecord.tpu import create_mesh

    import numpy as np

    mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
    rng = np.random.default_rng(0)
    params = {
        "w": jnp.asarray(rng.normal(size=(4, 2, 8, 8)) * 0.5, jnp.float32),
    }

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    p_sh = jax.device_put(params, NamedSharding(mesh, P("pipe")))
    stream = pipeline.PipelineStream(
        stage_fn, p_sh, mesh, n_virtual=2, microbatch_shape=(2, 8)
    )
    return stream.step_spec()


def _moe_fixture(cfg):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_tfrecord.models import moe

    params = moe.init_params(jax.random.key(0), cfg)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 16, cfg.d_model)),
        jnp.float32,
    )
    return params, x


def _build_moe_apply_ep():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_tfrecord.models import moe
    from tpu_tfrecord.tpu import create_mesh

    mesh = create_mesh({"expert": 4}, jax.devices()[:4])
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, top_k=2)
    params, x = _moe_fixture(cfg)
    sh = moe.param_shardings(mesh, expert_axis="expert")
    p_sh = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
    x_sh = jax.device_put(x, NamedSharding(mesh, P(None, "expert", None)))
    fn = jax.jit(lambda p, x: moe.moe_apply_ep(p, x, cfg, mesh))
    return fn, (p_sh, x_sh)


def _build_moe_apply_ep_diagnostics():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_tfrecord.models import moe
    from tpu_tfrecord.tpu import create_mesh

    cfg = moe.MoEConfig(d_model=8, d_ff=16, n_experts=4, top_k=2, capacity_factor=1.0)
    params = moe.init_params(jax.random.key(0), cfg)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(16, 8)), jnp.float32
    )
    mesh = create_mesh({"expert": 4, "data": 2})
    fn = jax.jit(
        lambda p, x: moe.moe_apply_ep(p, x, cfg, mesh, diagnostics=True)
    )
    return fn, (params, x)


def _build_lm_train_step():
    import functools

    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_tfrecord.models import lm
    from tpu_tfrecord.tpu import create_mesh

    cfg = lm.LMConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16,
        n_micro=4,
    )
    mesh = create_mesh({"pipe": 4, "data": 2})
    params = lm.init_params(jax.random.key(0), cfg)
    p_sh = jax.device_put(
        params, lm.param_shardings(mesh, params, pipe_axis="pipe")
    )
    tx = optax.sgd(1e-2)
    opt = jax.device_put(
        tx.init(params),
        jax.tree.map(lambda _: NamedSharding(mesh, P()), tx.init(params)),
    )
    toks = jax.numpy.asarray(lm.make_synthetic_tokens(cfg, 8, seed=0))
    step = jax.jit(
        functools.partial(
            lm.train_step, cfg=cfg, tx=tx, mesh=mesh, data_axis="data",
            pipe_axis="pipe",
        )
    )
    return step, (p_sh, opt, toks)


def _build_lm_train_step_fsdp():
    import functools

    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_tfrecord.models import lm
    from tpu_tfrecord.tpu import create_mesh

    cfg = lm.LMConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16,
    )
    mesh = create_mesh({"data": 2, "fsdp": 4})
    params = lm.init_params(jax.random.key(0), cfg)
    p_sh = jax.device_put(
        params, lm.param_shardings(mesh, params, fsdp_axis="fsdp")
    )
    tx = optax.sgd(1e-2)
    opt = tx.init(p_sh)  # zeros_like inherits the sharded placement
    toks = jax.device_put(
        jax.numpy.asarray(lm.make_synthetic_tokens(cfg, 8, seed=0)),
        NamedSharding(mesh, P("data", None)),
    )
    step = jax.jit(
        functools.partial(
            lm.train_step, cfg=cfg, tx=tx, mesh=mesh, data_axis="data",
            fsdp_axis="fsdp",
        )
    )
    return step, (p_sh, opt, toks)


def _build_lm_train_step_fsdp_pp():
    import functools

    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_tfrecord.models import lm
    from tpu_tfrecord.tpu import create_mesh

    cfg = lm.LMConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16,
        n_micro=4,
    )
    mesh = create_mesh({"pipe": 2, "data": 2, "fsdp": 2})
    params = lm.init_params(jax.random.key(0), cfg)
    p_sh = jax.device_put(
        params,
        lm.param_shardings(
            mesh, params, pipe_axis="pipe", fsdp_axis="fsdp"
        ),
    )
    tx = optax.sgd(1e-2)
    opt = tx.init(p_sh)
    toks = jax.device_put(
        jax.numpy.asarray(lm.make_synthetic_tokens(cfg, 8, seed=0)),
        NamedSharding(mesh, P("data", None)),
    )
    step = jax.jit(
        functools.partial(
            lm.train_step, cfg=cfg, tx=tx, mesh=mesh, data_axis="data",
            pipe_axis="pipe", fsdp_axis="fsdp",
        )
    )
    return step, (p_sh, opt, toks)


#: The manifest. Every historical inline pin appears here exactly once;
#: the diagnostics rows pin that the flag adds no forbidden collective
#: (its off twin is the same entrypoint's plain row).
CONTRACTS: Dict[str, HloContract] = {
    c.name: c
    for c in (
        HloContract(
            name="pipeline_feed_ring",
            entrypoint="models.pipeline.pipeline_apply",
            contains=("collective-permute",),
            absent=("all-gather", "all-reduce", "all-to-all"),
            builder=_build_pipeline_feed_ring,
            note="feed/activation/output movement is neighbor permutes of "
            "ONE microbatch slice; the old full-stream psum broadcast "
            "is banned outright",
        ),
        HloContract(
            name="pipeline_feed_ring_dp",
            entrypoint="models.pipeline.pipeline_apply (dp x pp)",
            contains=("collective-permute",),
            absent=("all-gather",),
            builder=_build_pipeline_feed_ring_dp,
            note="composing a data axis must not re-introduce a gather of "
            "the stream (all-reduce is dp's legitimate collective here)",
        ),
        HloContract(
            name="pipeline_interleaved",
            entrypoint="models.pipeline.pipeline_apply (n_virtual=2)",
            contains=("collective-permute",),
            absent=("all-gather", "all-reduce", "all-to-all"),
            builder=_build_pipeline_interleaved,
            note="interleaved virtual stages ride the SAME three O(mb) "
            "rings: cutting the bubble by V may not re-introduce a "
            "gather or broadcast of the stream",
        ),
        HloContract(
            name="pipeline_stream_step",
            entrypoint="models.pipeline.PipelineStream (per-tick step)",
            contains=("collective-permute",),
            absent=("all-gather", "all-reduce", "all-to-all"),
            builder=_build_pipeline_stream_step,
            note="the serving step's only data argument is ONE [mb, ...] "
            "slice; activations still hop by neighbor permute and "
            "nothing gathers",
        ),
        HloContract(
            name="pipeline_diagnostics",
            entrypoint="models.pipeline.pipeline_apply(diagnostics=True)",
            contains=("collective-permute",),
            absent=("all-gather",),
            builder=_build_pipeline_diagnostics,
            diagnostics=True,
            note="the bubble counter threads the schedule's own loop — "
            "identical per device, so no collective may be added",
        ),
        HloContract(
            name="moe_apply_ep",
            entrypoint="models.moe.moe_apply_ep",
            contains=("all-to-all",),
            absent=("all-gather",),
            builder=_build_moe_apply_ep,
            note="EP dispatch is an all-to-all; neither tokens nor expert "
            "weights are ever gathered",
        ),
        HloContract(
            name="moe_apply_ep_diagnostics",
            entrypoint="models.moe.moe_apply_ep(diagnostics=True)",
            contains=("all-to-all",),
            absent=("all-gather",),
            builder=_build_moe_apply_ep_diagnostics,
            diagnostics=True,
            note="diagnostics add [E]-sized psums, never a token gather",
        ),
        HloContract(
            name="lm_train_step",
            entrypoint="models.lm.train_step (dp x pp)",
            contains=("collective-permute",),
            absent=("all-gather",),
            builder=_build_lm_train_step,
            note="the acceptance pin at the train-step level; grads over "
            "'data' still all-reduce — dp's collective, not the pipeline's",
        ),
        HloContract(
            name="lm_train_step_fsdp",
            entrypoint="models.lm.train_step (dp x fsdp)",
            contains=("all-gather",),
            absent=("all-to-all", "collective-permute"),
            builder=_build_lm_train_step_fsdp,
            note="weight sharding gathers ON USE — the all-gathers are the "
            "forward's per-weight materializations; grads reduce on the "
            "SHARDED layout into sharded opt state (tests pin per-device "
            "param+opt bytes shrinking ~linearly in the fsdp axis, so no "
            "full gather of grads can hide here)",
        ),
        HloContract(
            name="lm_train_step_fsdp_pp",
            entrypoint="models.lm.train_step (dp x fsdp x pp)",
            contains=("collective-permute", "all-gather"),
            absent=("all-to-all",),
            builder=_build_lm_train_step_fsdp_pp,
            note="the full composed mesh: the pipeline's stream still moves "
            "ONLY by neighbor permute, while the stage weights — at rest "
            "P(pipe, fsdp, ...) — all-gather their fsdp dim once per step "
            "at the pipeline_apply param_spec boundary (gather-on-use "
            "composed under stage slicing)",
        ),
    )
}


def get(name: str) -> HloContract:
    try:
        return CONTRACTS[name]
    except KeyError:
        raise KeyError(
            f"unknown HLO contract {name!r}; known: {sorted(CONTRACTS)}"
        ) from None


def build(contract: HloContract) -> Tuple[Callable, Tuple]:
    return contract.builder()


def verify(name_or_contract, fn=None, args=None) -> str:
    """Compile one contract's entrypoint and assert its collective pins;
    returns the HLO text. Tests pass their OWN (fn, args) when they pin a
    construction they already built — the contract (contains/absent)
    still lives here; with fn omitted the manifest builder is used."""
    c = (
        name_or_contract
        if isinstance(name_or_contract, HloContract)
        else get(name_or_contract)
    )
    hlo_util = ensure_hlo_util()
    if fn is None:
        fn, args = build(c)
    hlo = hlo_util.compiled(fn, *args).as_text()
    for op in c.contains:
        assert op in hlo, (
            f"HLO contract {c.name}: expected {op!r} in compiled HLO of "
            f"{c.entrypoint}, not found"
        )
    for op in c.absent:
        assert op not in hlo, (
            f"HLO contract {c.name}: forbidden {op!r} present in compiled "
            f"HLO of {c.entrypoint}"
        )
    return hlo


def check_contracts(
    names: Optional[Iterable[str]] = None,
) -> List[Dict]:
    """The ``--hlo`` driver: build + compile + check every manifest row.
    Returns one dict per contract: {name, entrypoint, ok, error, skipped}.
    A missing optional dep (optax for the LM row) reports skipped, not
    failed — the static gate must run on codec-only installs."""
    results: List[Dict] = []
    for name in names if names is not None else sorted(CONTRACTS):
        c = get(name)
        entry = {
            "name": c.name, "entrypoint": c.entrypoint, "ok": False,
            "error": None, "skipped": False,
        }
        try:
            verify(c)
            entry["ok"] = True
        except ImportError as e:
            entry["skipped"] = True
            entry["error"] = f"optional dependency missing: {e}"
        except AssertionError as e:
            entry["error"] = str(e)
        except Exception as e:  # build/compile failure is a finding too  # graftlint: swallow(failure captured into the result row the driver reports)
            entry["error"] = f"{type(e).__name__}: {e}"
        results.append(entry)
    return results
