#!/usr/bin/env python
"""tfrecord_doctor: offline scan/salvage for corrupt TFRecord shards.

The offline complement to the online ``on_corrupt`` read policy: where the
dataset pipeline resyncs past bad frames at training time, the doctor finds
them ahead of time and (with ``--repair``) rewrites a shard keeping every
valid record — so a fleet job can quarantine or fix corrupt inputs instead
of paying the salvage cost every epoch.

Usage::

    tools/tfrecord_doctor.py DATA_DIR_OR_FILE...          # scan + report
    tools/tfrecord_doctor.py --repair bad.tfrecord        # + salvage copy
    tools/tfrecord_doctor.py --repair --out fixed.tfrecord bad.tfrecord
    tools/tfrecord_doctor.py --simulate plan.json shard   # chaos repro
    tools/tfrecord_doctor.py cache CACHE_DIR              # epoch-cache audit
    tools/tfrecord_doctor.py cache --evict-stale CACHE_DIR
    tools/tfrecord_doctor.py report DATA_DIR              # bottleneck doctor
    tools/tfrecord_doctor.py tune DATA_DIR                # offline autotune
    tools/tfrecord_doctor.py fleet SPOOL_DIR              # cluster doctor
    tools/tfrecord_doctor.py train SPOOL_DIR              # training doctor
    tools/tfrecord_doctor.py serve SPOOL_DIR              # serving doctor
    tools/tfrecord_doctor.py slo SPOOL_DIR                # error-budget doctor
    tools/tfrecord_doctor.py merge-trace OUT F1 F2 ...    # fuse Perfetto traces

``fleet``, ``train``, ``serve``, ``slo``, and ``serve-status`` accept
``--json``: the same
event objects, in the same order, as ONE machine-readable JSON document
``{"events": [...]}`` instead of one object per line (exit codes
unchanged — pinned by round-trip tests).

The ``train`` subcommand is the TRAINING doctor: it reads the same spool
directory as ``fleet`` but explains trainer processes — per-trainer step
p50/p99 and steps/s, the step-phase decomposition
(``train.data_wait``/``h2d``/``compute``/``ckpt`` shares), the
input/compute/ckpt-bound training verdict, and the in-jit model
diagnostics (MoE expert imbalance / dropped fraction / gate entropy,
measured pipeline bubble) when the trainer folded them. Exit 0 = report;
2 = no trainer spools.

The ``report`` subcommand is the bottleneck doctor: it runs N batches of
the real pipeline with the flight recorder on (tpu_tfrecord.telemetry)
and prints where the time went — one ``{"event": "stage", ...}`` line per
pipeline stage (seconds, records, p50/p99 latency), one
``{"event": "shard", ...}`` line per slowest shard (span-attributed
seconds), and a final ``{"event": "report", ...}`` line with the
straggler ratio (decode p99/p50) and the producer/consumer bound-ness
verdict — "is this pipeline decode-bound or is the consumer the
bottleneck?" answered without attaching a profiler. ``--trace-out
FILE.json`` additionally saves the Chrome trace (open in Perfetto).

The ``tune`` subcommand runs the closed-loop autotuner
(tpu_tfrecord.autotune) offline: it reads the real pipeline with
``autotune="on"`` for ``--seconds``, letting the controller climb from the
starting knobs, then prints one ``{"event": "tune_step", ...}`` line per
controller decision (the convergence trajectory) and a final
``{"event": "tune", ...}`` line with the converged knob set and the
throughput it reached — the values to bake into a fixed-knob production
config for this box/dataset pair.

The ``fleet`` subcommand is the cluster doctor (tpu_tfrecord.fleet): it
aggregates a telemetry spool directory — one JSONL file per process, each
process of a job spooling with ``telemetry_spool_dir`` pointed at the
same dir — and prints one ``{"event": "proc", ...}`` line per process
(host/pid/role, liveness + heartbeat age, decode throughput, stage
p50/p99, per-process bound-ness verdict) and a final
``{"event": "fleet", ...}`` line with the cluster-level counters (exact
sums), cluster latency quantiles (exact histogram-bucket merges), the
dead-process list, and the cluster verdict — "which worker is slow, which
worker is DEAD, and is the fleet producer- or consumer-bound" answered
from files alone, no live processes required.

The ``serve`` subcommand is the SERVING doctor (tpu_tfrecord.serving):
it reads the same spool directory as ``fleet`` but explains the
continuous-batching tier — one ``{"event": "replica", ...}`` line per
serving replica (request latency p50/p99, admission queue depth,
in-flight slots, shed counts: rejected / deadline_expired / disconnects)
and a final ``{"event": "serve", ...}`` line with exact merged latency
quantiles, fleet shed totals, and the SLO verdict against ``--slo-ms``:
``meeting_slo`` (p99 under target), ``queue_bound`` (missing SLO with a
filling admission queue — add replicas), ``compute_bound`` (missing SLO
with an empty queue — faster model/hardware, not more replicas). Exit
0 = report (an overloaded tier is a finding), 2 = no serving spools.

The ``slo`` subcommand is the ERROR-BUDGET doctor (tpu_tfrecord.slo): it
replays a spool directory's whole cumulative history into the SLO engine
and prints one ``{"event": "objective", ...}`` line per declared
objective (``--objective availability:0.999`` /
``latency:0.95:250``, repeatable; both by default) with budget remaining
and the fast/slow multi-window burn rates, plus a final
``{"event": "slo", ...}`` line whose verdict is
``healthy`` / ``slow_burn`` / ``fast_burn`` — "are we burning the error
budget fast enough to page someone", not "is p99 high right now". Exit
0 = report; 2 = no spool snapshots.

The ``serve-status`` subcommand is the data-service doctor
(tpu_tfrecord.service): one status round trip to a dispatcher prints one
``{"event": "worker", ...}`` line per registered decode worker (liveness
by heartbeat age vs the lease TTL, current shard leases, shards done) and
a final ``{"event": "service", ...}`` line with the service totals
(alive/dead workers, active leases, shards done, lease reassignments,
trace id) — "which worker holds the lease, which worker is dead, and did
any shard get reassigned" answered from one RPC. Exit 0 = report (dead
workers are a finding), 2 = dispatcher unreachable.

``merge-trace OUT F1 F2 ...`` fuses K per-process Chrome trace files
(``save_chrome_trace`` output) into one Perfetto timeline with a labeled
track per process (telemetry.merge_chrome_traces) — pid collisions
across hosts are remapped, every process renders under its
``role@host:pid`` label. A DIRECTORY argument stands for every
``*.json`` inside it, sorted — ``merge-trace merged.json traces/``
fuses a whole run's trace drop without hand-globbing.

The ``cache`` subcommand audits a columnar epoch cache directory
(tpu_tfrecord.cache): one ``{"event": "cache_entry", ...}`` line per entry
with its fingerprint, source shard, size, chunk/row counts, and CRC-verify
status (``ok`` | ``stale`` | ``corrupt`` | ``source_missing``);
``--evict-stale`` deletes entries whose source shard changed or vanished
(corrupt entries are reported but kept for inspection unless
``--evict-corrupt`` is also given).

``--simulate plan.json`` replays a deterministic fault plan
(tpu_tfrecord.faults.FaultPlan JSON) against the scan — the repro half of
a chaos bug report: the plan that reproduced a field failure in tests can
be re-run against the real shard, and the emitted ``fault`` events (the
plan's ledger) show exactly which injected faults fired where.

Output is line-oriented JSON on stdout (machine-first; pipe to ``jq`` for
humans): one ``{"event": "corrupt", ...}`` line per corrupt region (path,
offset, kind, resync_offset, bytes_skipped) and one
``{"event": "summary", ...}`` line per file (records, corrupt_events,
repaired_path when --repair ran). Any codec the reader supports works —
the codec is inferred from the extension, and repaired files keep it.

Exit status: 0 = every file clean, 1 = corruption found (salvaged if
--repair), 2 = a file could not be scanned at all.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, Iterator, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_tfrecord import wire  # noqa: E402
from tpu_tfrecord.io.paths import discover_shards  # noqa: E402
from tpu_tfrecord.io.reader import salvage_spans_stream  # noqa: E402


def iter_valid_records(
    path: str, events: List[Dict], max_record_bytes: int
) -> Iterator[bytes]:
    """Yield every valid record payload in ``path``, appending one dict per
    corrupt region to ``events``."""
    for buf, offsets, lengths in salvage_spans_stream(
        path,
        on_event=events.append,
        max_record_bytes=max_record_bytes,
    ):
        for off, length in zip(offsets.tolist(), lengths.tolist()):
            yield bytes(buf[off : off + length])


def default_repair_path(path: str) -> str:
    """``x.tfrecord.gz`` -> ``_repaired-x.tfrecord.gz``. The leading
    underscore keeps the copy INVISIBLE to shard discovery (like _SUCCESS):
    a dataset dir that was doctored in place must not serve both the
    corrupt original and the salvaged copy to the next read, and a second
    doctor run must not re-scan repaired output. The full original name is
    preserved so codec inference by extension keeps working; reading the
    repaired file by its explicit path bypasses the hidden-file filter."""
    base = os.path.basename(path)
    return os.path.join(os.path.dirname(path), "_repaired-" + base)


def doctor_file(
    path: str,
    repair: bool,
    out_path: Optional[str],
    max_record_bytes: int,
    emit,
) -> Dict:
    """Scan (and optionally repair) one shard; emit event lines; return the
    summary dict (also emitted)."""
    events: List[Dict] = []
    records = 0
    repaired_path = None
    codec = wire.codec_from_path(path)
    if repair:
        repaired_path = out_path or default_repair_path(path)
        with wire.open_compressed(repaired_path, "wb", codec) as fh:
            w = wire.RecordWriter(fh)
            for rec in iter_valid_records(path, events, max_record_bytes):
                w.write(rec)
                records += 1
    else:
        for _ in iter_valid_records(path, events, max_record_bytes):
            records += 1
    for ev in events:
        emit({"event": "corrupt", "path": path, **ev})
    summary = {
        "event": "summary",
        "path": path,
        "records": records,
        "corrupt_events": len(events),
        "bytes_skipped": sum(int(e.get("bytes_skipped") or 0) for e in events),
    }
    if repair:
        if events or out_path is not None:
            # an explicit --out is a contract: the caller consumes that
            # path whether or not the input turned out corrupt
            summary["repaired_path"] = repaired_path
        else:
            # clean input, implicit default path: don't leave a redundant
            # (and discovery-hidden) copy behind
            try:
                os.remove(repaired_path)
            except OSError:
                pass
    emit(summary)
    return summary


def expand_paths(inputs: List[str]) -> List[str]:
    """Files pass through; directories/globs expand to their data shards.
    Scheme'd sources (``http(s)://``, ``gs://``, ...) resolve through the
    pluggable FS layer, so ``tfrecord_doctor scan`` reads remote shards
    over the same connectors the pipeline uses."""
    from tpu_tfrecord import fs as _fs

    out: List[str] = []
    for item in inputs:
        if _fs.has_scheme(item):
            if _fs.filesystem_for(item).isfile(item):
                out.append(item)
            else:
                out.extend(sh.path for sh in discover_shards(item))
        elif os.path.isfile(item):
            out.append(item)
        else:
            out.extend(sh.path for sh in discover_shards(item))
    return out


def cache_main(argv: List[str]) -> int:
    """The ``cache`` subcommand: audit (and optionally prune) a columnar
    epoch cache directory. Exit 0 = every entry ok; 1 = stale/corrupt/
    orphaned entries found (evicted ones still count); 2 = unreadable dir."""
    from tpu_tfrecord import cache as cache_mod

    ap = argparse.ArgumentParser(
        prog="tfrecord_doctor cache",
        description="List/verify columnar epoch cache entries",
    )
    ap.add_argument("cache_dirs", nargs="+", help="cache directories")
    ap.add_argument(
        "--evict-stale", action="store_true",
        help="delete entries whose source shard changed or vanished",
    )
    ap.add_argument(
        "--evict-corrupt", action="store_true",
        help="with --evict-stale semantics for CRC-corrupt entries too",
    )
    args = ap.parse_args(argv)

    def emit(obj: Dict) -> None:
        sys.stdout.write(json.dumps(obj, sort_keys=True) + "\n")

    rc = 0
    for cache_dir in args.cache_dirs:
        if not os.path.isdir(cache_dir):
            emit({"event": "error", "path": cache_dir, "error": "not a directory"})
            rc = 2
            continue
        counts: Dict[str, int] = {}
        evicted = 0
        try:
            # materialized up front: an unreadable dir must exit 2, not
            # read as a healthy empty cache
            reports = list(cache_mod.iter_entry_reports(cache_dir))
        except OSError as e:
            emit({"event": "error", "path": cache_dir, "error": str(e)})
            rc = 2
            continue
        for report in reports:
            status = report["status"]
            counts[status] = counts.get(status, 0) + 1
            drop = (
                args.evict_stale and status in ("stale", "source_missing")
            ) or (args.evict_corrupt and status == "corrupt")
            if drop:
                try:
                    os.remove(report["entry"])
                    report = dict(report, evicted=True)
                    evicted += 1
                except OSError as e:
                    report = dict(report, evicted=False, evict_error=str(e))
            emit({"event": "cache_entry", **report})
        emit(
            {
                "event": "cache_summary",
                "path": cache_dir,
                "entries": sum(counts.values()),
                "evicted": evicted,
                **{f"status_{k}": v for k, v in sorted(counts.items())},
            }
        )
        if rc == 0 and any(k != "ok" for k in counts):
            rc = 1
    return rc


def report_main(argv: List[str]) -> int:
    """The ``report`` subcommand: run N batches with tracing on and print
    the stage breakdown, slowest shards, straggler ratio, and the
    bound-ness verdict. Exit 0 = report produced (slow is not an error);
    2 = the dataset could not be read at all."""
    ap = argparse.ArgumentParser(
        prog="tfrecord_doctor report",
        description="Bottleneck doctor: trace a real read and explain it",
    )
    ap.add_argument("data_dir", help="dataset directory (or shard glob)")
    ap.add_argument(
        "--batches", type=int, default=32,
        help="batches to run before reporting (default 32)",
    )
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument(
        "--workers", type=int, default=1,
        help="parallel decode workers (num_workers) for the probe read",
    )
    ap.add_argument(
        "--top", type=int, default=5,
        help="slowest shards to report (default 5)",
    )
    ap.add_argument(
        "--trace-out", default=None, metavar="FILE",
        help="save the Chrome trace-event JSON here (open in Perfetto)",
    )
    args = ap.parse_args(argv)

    from tpu_tfrecord import telemetry
    from tpu_tfrecord.io.dataset import TFRecordDataset
    from tpu_tfrecord.metrics import METRICS

    def emit(obj: Dict) -> None:
        sys.stdout.write(json.dumps(obj, sort_keys=True) + "\n")

    METRICS.reset()
    telemetry.RECORDER.clear()
    rows = 0
    batches = 0
    try:
        # --batches epochs is enough to fill --batches batches on ANY
        # non-empty dataset (each epoch yields >= 1 batch with
        # drop_remainder=False) while still TERMINATING on a dataset whose
        # shards hold zero records — num_epochs=None would spin forever
        # there, and the doctor must always exit
        ds = TFRecordDataset(
            args.data_dir,
            batch_size=args.batch_size,
            num_workers=args.workers,
            drop_remainder=False,
            num_epochs=max(1, args.batches),
            trace="on",
        )
        with ds.batches() as it:
            for cb in it:
                rows += cb.num_rows
                batches += 1
                if batches >= args.batches:
                    break
    except Exception as e:  # unreadable dataset, not a slow one  # graftlint: swallow(error event emitted + exit 2)
        emit({"event": "error", "path": args.data_dir, "error": str(e)})
        return 2
    finally:
        telemetry.disable()

    for name, entry in sorted(METRICS.snapshot().items()):
        if not entry.get("seconds"):
            # gauges (no "seconds" key) land in the final line; pure
            # count()-style counters (seconds == 0.0) are not pipeline
            # stages — they are already the report's "counters" map
            continue
        line: Dict = {
            "event": "stage",
            "stage": name,
            "seconds": round(entry["seconds"], 6),
            "records": int(entry["records"]),
        }
        ms = telemetry.quantiles_ms({name: entry}).get(name)
        if ms:
            line.update({k: v for k, v in ms.items() if k != "count"})
        emit(line)

    # span-attributed per-shard time: which shards the pipeline actually
    # spent its open/read/decode/serve time on (stragglers by name)
    per_shard: Dict[str, Dict] = {}
    for name, _t0, dur, _tid, attrs, ph in telemetry.RECORDER.spans():
        shard = (attrs or {}).get("shard")
        if ph != "X" or shard is None:
            continue
        agg = per_shard.setdefault(shard, {"seconds": 0.0, "spans": 0})
        agg["seconds"] += dur / 1e9
        agg["spans"] += 1
    ranked = sorted(
        per_shard.items(), key=lambda kv: kv[1]["seconds"], reverse=True
    )
    for path, agg in ranked[: args.top]:
        emit(
            {
                "event": "shard",
                "path": path,
                "seconds": round(agg["seconds"], 6),
                "spans": agg["spans"],
            }
        )

    q = METRICS.quantiles().get("decode") or {}
    straggler = (
        round(q["p99_s"] / q["p50_s"], 2) if q.get("p50_s") else None
    )
    occupancy = METRICS.gauge_value(telemetry.OCCUPANCY_GAUGE)
    report = {
        "event": "report",
        "path": args.data_dir,
        "batches": batches,
        "rows": rows,
        # decode straggler spread: p99/p50 chunk latency (1.x = uniform;
        # >>1 = a few chunks/shards dominate — look at the shard lines)
        "straggler_p99_p50": straggler,
        "prefetch_occupancy": (
            round(occupancy, 4) if occupancy is not None else None
        ),
        "verdict": telemetry.boundness_verdict(occupancy),
        "counters": {
            name: int(totals[0])
            for name, totals in sorted(METRICS.raw_totals().items())
            if totals[3] == 0.0 and totals[1] == 0
        },
        "spans_recorded": len(telemetry.RECORDER),
        "spans_dropped": telemetry.RECORDER.dropped,
    }
    if ranked:
        report["slowest_shard"] = ranked[0][0]
    if args.trace_out is not None:
        telemetry.RECORDER.save_chrome_trace(args.trace_out)
        report["trace_path"] = args.trace_out
    emit(report)
    return 0


def tune_main(argv: List[str]) -> int:
    """The ``tune`` subcommand: run the autotune loop offline and print
    the converged knob set. Exit 0 = tuned (even if nothing moved);
    2 = the dataset could not be read at all."""
    ap = argparse.ArgumentParser(
        prog="tfrecord_doctor tune",
        description="Offline autotune: converge the pipeline knobs on a "
        "real read and print the result",
    )
    ap.add_argument("data_dir", help="dataset directory (or shard glob)")
    ap.add_argument(
        "--seconds", type=float, default=5.0,
        help="how long to let the controller climb (default 5)",
    )
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument(
        "--workers", type=int, default=1,
        help="starting decode workers (default 1: climb from the floor)",
    )
    ap.add_argument(
        "--interval", type=float, default=0.25,
        help="controller tick interval in seconds (default 0.25)",
    )
    args = ap.parse_args(argv)

    import time

    from tpu_tfrecord.io.dataset import TFRecordDataset
    from tpu_tfrecord.metrics import METRICS

    def emit(obj: Dict) -> None:
        sys.stdout.write(json.dumps(obj, sort_keys=True) + "\n")

    METRICS.reset()
    rows = 0
    tuner = None
    try:
        ds = TFRecordDataset(
            args.data_dir,
            batch_size=args.batch_size,
            num_workers=args.workers,
            drop_remainder=False,
            # finite epoch bound so a zero-record dataset terminates
            # instead of spinning; any real dataset re-epochs far past
            # --seconds before exhausting it
            num_epochs=10_000,
            autotune="on",
            autotune_interval_s=args.interval,
        )
        with ds.batches() as it:
            tuner = it.autotune
            # the clock starts at the read loop, not at dataset
            # construction: shard discovery/opens must not deflate the
            # rows_per_sec a reader bakes into a production config
            t0 = time.perf_counter()
            deadline = t0 + args.seconds
            for cb in it:
                rows += cb.num_rows
                if time.perf_counter() >= deadline:
                    break
            elapsed = time.perf_counter() - t0
    except Exception as e:  # unreadable dataset, not a slow one  # graftlint: swallow(error event emitted + exit 2)
        emit({"event": "error", "path": args.data_dir, "error": str(e)})
        return 2
    for decision in tuner.log:
        emit({"event": "tune_step", **decision})
    emit(
        {
            "event": "tune",
            "path": args.data_dir,
            "seconds": round(elapsed, 3),
            "rows": rows,
            "rows_per_sec": round(rows / elapsed, 1) if elapsed else None,
            "start_workers": args.workers,
            "adjustments": len(tuner.log),
            "knobs": tuner.snapshot(),
        }
    )
    return 0


class _Emitter:
    """The doctor's one stdout owner. Default: one JSON object per line
    (the machine-first text format every subcommand always emitted).
    With ``--json`` the SAME objects, in the SAME order, are buffered and
    dumped as ONE machine-readable document ``{"events": [...]}`` at the
    end — a round-trip mirror of the text lines (pinned by tests), with
    exit codes unchanged. Call sites wrap their body in try/finally so
    every return path lands the document."""

    def __init__(self, as_doc: bool = False):
        self.as_doc = as_doc
        self.events: List[Dict] = []

    def __call__(self, obj: Dict) -> None:
        if self.as_doc:
            self.events.append(obj)
        else:
            sys.stdout.write(json.dumps(obj, sort_keys=True) + "\n")

    def close(self) -> None:
        if self.as_doc:
            sys.stdout.write(
                json.dumps({"events": self.events}, sort_keys=True) + "\n"
            )


def _add_json_flag(ap: argparse.ArgumentParser) -> None:
    ap.add_argument(
        "--json", action="store_true",
        help="emit one JSON document {\"events\": [...]} mirroring the "
        "text lines (same objects, same order, same exit code)",
    )


def fleet_main(argv: List[str]) -> int:
    """The ``fleet`` subcommand: aggregate a telemetry spool dir and print
    the cluster picture. Exit 0 = report produced (dead workers are a
    finding, not a failure); 2 = unreadable spool dir or no spool files."""
    ap = argparse.ArgumentParser(
        prog="tfrecord_doctor fleet",
        description="Cluster doctor: merge per-process telemetry spools "
        "and explain the fleet",
    )
    ap.add_argument("spool_dir", help="telemetry spool directory")
    ap.add_argument(
        "--stale-after", type=float, default=None, metavar="SECONDS",
        help="heartbeat age beyond which a process is dead "
        "(default: 2x each process's own snapshot interval)",
    )
    ap.add_argument(
        "--trace-id", default=None, metavar="ID",
        help="only merge spool files from this run (a reused spool dir "
        "keeps previous runs' files; the fleet line's trace_ids list "
        "shows what is mixed in)",
    )
    _add_json_flag(ap)
    args = ap.parse_args(argv)

    emit = _Emitter(args.json)
    try:
        return _fleet_report(args, emit)
    finally:
        emit.close()


def _fleet_report(args, emit) -> int:
    from tpu_tfrecord import fleet, telemetry

    try:
        agg = fleet.TelemetryAggregator(
            args.spool_dir, stale_after_s=args.stale_after,
            trace_id=args.trace_id,
        )
        snap = agg.aggregate()
    except Exception as e:  # graftlint: swallow(error event emitted + exit 2)
        # unreadable dir, or spool contents the aggregator cannot merge —
        # either way the documented contract is an error line + exit 2,
        # never a traceback
        emit({"event": "error", "path": args.spool_dir, "error": str(e)})
        return 2
    if not snap.processes:
        # distinguish an empty/missing spool dir from a --trace-id filter
        # that matched nothing: the latter sends the operator to the
        # filter (typo'd or stale id), not to a directory that is in fact
        # full of spool files from other runs
        err: Dict = {"event": "error", "path": args.spool_dir}
        present = (
            [s.trace_id for s in fleet.TelemetryAggregator(
                args.spool_dir, clock=agg._clock).processes()]
            if args.trace_id is not None else []
        )
        if present:
            err["error"] = (
                f"no spool files match trace_id {args.trace_id!r}"
            )
            err["spool_files"] = len(present)
            err["trace_ids_present"] = sorted(
                {t for t in present if t}
            )
        else:
            err["error"] = "no spool files found"
        emit(err)
        return 2
    now = agg._clock()
    dead_ids = {id(p) for p in snap.dead}
    for p in snap.processes:
        decode = p.stages.get("decode") or p.stages.get("cache.serve")
        # throughput over the process's WALL observation window (spool
        # start -> last heartbeat, both on the writer's clock): stage
        # seconds are cumulative busy time summed across decode threads,
        # and dividing by those would understate a parallel worker by
        # its thread count
        wall = p.heartbeat - p.created if p.created else 0.0
        # a process that recorded train phases is a TRAINER: its verdict
        # is the step-phase one (input/compute/ckpt bound), not the
        # prefetch-occupancy one readers get
        shares = fleet.train_phase_shares(p)
        line: Dict = {
            "event": "proc",
            "host": p.host,
            "pid": p.pid,
            "role": p.role,
            "alive": id(p) not in dead_ids,
            # a clean-shutdown final snapshot: finished, never flagged dead
            **({"finished": True} if p.final else {}),
            "heartbeat_age_s": round(p.heartbeat_age(now), 3),
            "seq": p.seq,
            "records_per_sec": (
                round(decode[0] / wall, 1)
                if decode and wall > 0 else None
            ),
            "verdict": (
                telemetry.training_verdict(shares)
                if shares is not None
                else telemetry.boundness_verdict(
                    p.gauges.get(telemetry.OCCUPANCY_GAUGE)
                )
            ),
        }
        try:
            q = fleet.quantiles_ms_from_states(p.hists)
        except Exception:  # graftlint: swallow(one corrupt hist state drops its quantiles, keeps the report)
            q = None  # one process's corrupt hist state: drop its
            # quantiles, keep its line (and the rest of the report)
        if q:
            line["quantiles"] = q
        if p.skipped_lines:
            line["skipped_lines"] = p.skipped_lines
        emit(line)
    emit(
        {
            "event": "fleet",
            "path": args.spool_dir,
            "processes": len(snap.processes),
            "alive": len(snap.alive),
            "finished": sum(1 for p in snap.processes if p.final),
            "dead": [
                {"host": p.host, "pid": p.pid, "role": p.role,
                 "heartbeat_age_s": round(p.heartbeat_age(now), 3)}
                for p in snap.dead
            ],
            "counters": snap.counters,
            "stages": {
                name: {"records": t[0], "bytes": t[1], "seconds": round(t[3], 6)}
                for name, t in sorted(snap.stages.items())
            },
            "quantiles": telemetry.quantiles_ms(snap.quantiles()),
            "occupancy": (
                round(snap.occupancy, 4) if snap.occupancy is not None else None
            ),
            "verdict": snap.verdict,
            "trace_ids": sorted(
                {p.trace_id for p in snap.processes if p.trace_id}
            ),
        }
    )
    return 0


def serve_status_main(argv: List[str]) -> int:
    """The ``serve-status`` subcommand: one status round trip per data
    service partition (tpu_tfrecord.service) — ``dispatcher`` is a single
    host:port or a full partition-map spec (``h:p1|h:p2,h:p3`` /
    ``@map.json``), and each partition is asked preferring the acting
    primary (a member answering as a warm standby still counts: the
    partition is alive). Per partition: one ``worker`` line per
    registered worker (liveness, draining flag, current leases, shards
    done, heartbeat age; the fleet doctor's per-proc rendering
    vocabulary), one ``tenant`` line per decode fingerprint (consumers /
    jobs / leases / warm-cache hit ratio — the multi-tenant sharing
    picture), and one ``service`` summary line carrying the HA fields
    (role, generation, failed_over, demoted). One ``scaler`` line when an
    elastic FleetScaler is attached (the federated scaler publishes the
    same block to every partition, so it is emitted once), and — under a
    multi-partition map — one federated ``ha`` summary (partitions
    answered, acting primaries, failovers observed, distinct workers
    across partitions). Exit 0 = every partition answered by someone
    (dead workers and a completed failover are findings, not failures);
    2 = some partition fully unreachable or a member is not a
    dispatcher."""
    ap = argparse.ArgumentParser(
        prog="tfrecord_doctor serve-status",
        description="Data-service doctor: ask the dispatcher(s) who is "
        "serving what",
    )
    ap.add_argument(
        "dispatcher",
        help="dispatcher host:port, or a partition-map spec "
        "('h:p1|h:p2,h:p3' — comma-separated partitions, each "
        "primary|standby — or '@map.json')",
    )
    ap.add_argument(
        "--timeout", type=float, default=5.0, metavar="SECONDS",
        help="connect/request deadline (default 5s)",
    )
    _add_json_flag(ap)
    args = ap.parse_args(argv)

    emit = _Emitter(args.json)
    try:
        return _serve_status_report(args, emit)
    finally:
        emit.close()


def _serve_status_report(args, emit) -> int:
    from tpu_tfrecord import service

    try:
        pmap = service.PartitionMap.parse(args.dispatcher)
    except (OSError, ValueError) as e:
        emit({"event": "error", "path": args.dispatcher, "error": str(e)})
        return 2

    ok = True
    scaler_emitted = False
    all_workers: set = set()
    acting, failovers, generations = 0, 0, []
    for part in range(pmap.k):
        status, addr_used, best, errors = None, None, None, []
        for addr in pmap.addrs(part):
            try:
                st = service.fetch_status(addr, timeout=args.timeout)
            except (OSError, ValueError) as e:
                errors.append(f"{addr}: {e}")
                continue
            if not st.get("ok") or st.get("role") not in (
                "dispatcher", "standby"
            ):
                errors.append(
                    f"{addr}: "
                    f"{st.get('error') or f'not a dispatcher: {st!r}'}"
                )
                continue
            if st.get("role") == "dispatcher" and st.get("accepting", True):
                status, addr_used = st, addr
                break  # the acting primary answered — done here
            if best is None:
                # a standby (or demoted primary) answered: the partition
                # is alive, but keep scanning for the acting primary
                best = (st, addr)
        if status is None and best is not None:
            status, addr_used = best
        if status is None:
            ok = False
            emit({
                "event": "error", "partition": part,
                "path": "|".join(pmap.addrs(part)),
                "error": "; ".join(errors) or "unreachable",
            })
            continue
        if status.get("accepting", True) and status.get("role") == "dispatcher":
            acting += 1
        if status.get("failed_over"):
            failovers += 1
        generations.append(status.get("generation", 0))
        for w in status.get("workers", []):
            all_workers.add(w["worker_id"])
        scaler_emitted = _emit_partition_status(
            emit, part, addr_used, status,
            emit_scaler=not scaler_emitted,
        ) or scaler_emitted
    if pmap.k > 1:
        emit({
            "event": "ha",
            "path": args.dispatcher,
            "partitions": pmap.k,
            "answered": len(generations),
            "acting_primaries": acting,
            "failed_over": failovers,
            "generations": generations,
            "workers": len(all_workers),
        })
    return 0 if ok else 2


def _emit_partition_status(emit, part, addr_used, status,
                           emit_scaler=True) -> bool:
    """One partition's worker/tenant/scaler/service lines. Returns True
    when a scaler line was emitted (a federated scaler publishes the same
    block everywhere, so the caller emits it at most once)."""
    for w in status.get("workers", []):
        emit({
            "event": "worker",
            "partition": part,
            "worker_id": w["worker_id"],
            "addr": w["addr"],
            "pid": w["pid"],
            "alive": w["alive"],
            "draining": w.get("draining", False),
            "heartbeat_age_s": w["heartbeat_age_s"],
            "leases": w["leases"],
            "shards_done": w["shards_done"],
        })
    # one line per tenant (decode fingerprint): who shares this lease
    # table, and how much of its work the warm cache absorbed
    for t, info in sorted(status.get("tenants", {}).items()):
        completions = info.get("completions", 0)
        emit({
            "event": "tenant",
            "partition": part,
            "tenant": t,
            "consumers": info.get("consumers", 0),
            "jobs": info.get("jobs", 0),
            "leases": info.get("leases", 0),
            "shards_done": info.get("shards_done", 0),
            "completions": completions,
            "shared_cache_hits": info.get("shared_cache_hits", 0),
            "cache_hit_ratio": (
                round(info.get("shared_cache_hits", 0) / completions, 3)
                if completions else None
            ),
        })
    scaler = status.get("scaler")
    scaler_shown = False
    if scaler is not None and emit_scaler:
        scaler_shown = True
        emit({
            "event": "scaler",
            "workers": scaler.get("workers"),
            "min_workers": scaler.get("min_workers"),
            "max_workers": scaler.get("max_workers"),
            "draining": scaler.get("draining", []),
            "verdict": scaler.get("verdict"),
            "last_decision": scaler.get("last_decision"),
            "scale_ups": scaler.get("scale_ups", 0),
            "scale_downs": scaler.get("scale_downs", 0),
            "drains_completed": scaler.get("drains_completed", 0),
        })
    emit({
        "event": "service",
        "partition": part,
        "path": addr_used,
        "role": status.get("role"),
        "generation": status.get("generation", 0),
        "accepting": status.get("accepting", True),
        "failed_over": status.get("failed_over", False),
        "demoted": status.get("demoted", False),
        "workers": len(status.get("workers", [])),
        "alive": status.get("alive", 0),
        "draining": status.get("draining", []),
        "tenants": len(status.get("tenants", {})),
        "dead": [
            {"worker_id": w["worker_id"], "addr": w["addr"],
             "heartbeat_age_s": w["heartbeat_age_s"]}
            for w in status.get("workers", []) if not w["alive"]
        ],
        "lease_ttl_s": status.get("lease_ttl_s"),
        "active_leases": status.get("active_leases", 0),
        "shards_done": status.get("shards_done", 0),
        "lease_reassignments": status.get("lease_reassignments", 0),
        "trace_id": status.get("trace_id"),
    })
    return scaler_shown


def train_main(argv: List[str]) -> int:
    """The ``train`` subcommand: the trainer-side cluster doctor. Reads
    the same telemetry spool directory as ``fleet`` but explains the
    TRAINING loop: one ``{"event": "trainer", ...}`` line per spooling
    trainer process (step count + p50/p99 step latency, steps/s over the
    wall window, phase shares, the input/compute/ckpt-bound verdict, the
    MoE expert-imbalance line and the measured pipeline bubble when the
    in-jit model diagnostics ran) and one final ``{"event": "train", ...}``
    summary (merged step quantiles — exact histogram-bucket merges —
    fleet-level phase shares weighted by phase seconds, the fleet
    training verdict). Exit 0 = report produced; 2 = unreadable spool dir
    or no trainer spools in it."""
    ap = argparse.ArgumentParser(
        prog="tfrecord_doctor train",
        description="Training doctor: explain where trainer steps went",
    )
    ap.add_argument("spool_dir", help="telemetry spool directory")
    ap.add_argument(
        "--stale-after", type=float, default=None, metavar="SECONDS",
        help="heartbeat age beyond which a trainer is dead "
        "(default: 2x each process's own snapshot interval)",
    )
    ap.add_argument(
        "--trace-id", default=None, metavar="ID",
        help="only read spool files from this run",
    )
    ap.add_argument(
        "--role", default="trainer", metavar="ROLE",
        help="telemetry role that marks a trainer (default: trainer); "
        "processes with train.* phases recorded qualify regardless",
    )
    _add_json_flag(ap)
    args = ap.parse_args(argv)

    emit = _Emitter(args.json)
    try:
        return _train_report(args, emit)
    finally:
        emit.close()


def _train_report(args, emit) -> int:
    from tpu_tfrecord import fleet, telemetry
    from tpu_tfrecord.telemetry import Histogram

    try:
        agg = fleet.TelemetryAggregator(
            args.spool_dir, stale_after_s=args.stale_after,
            trace_id=args.trace_id,
        )
        # the aggregator owns liveness semantics (final-snapshot
        # handling, the 2x-interval default, the injectable clock):
        # reusing its classification keeps `doctor train` and
        # `doctor fleet` agreeing about the same spool file
        snap = agg.aggregate()
    except Exception as e:  # graftlint: swallow(error event emitted + exit 2)
        emit({"event": "error", "path": args.spool_dir, "error": str(e)})
        return 2
    procs = snap.processes
    dead_ids = {id(p) for p in snap.dead}
    # a trainer is anything stamped with the trainer role OR anything
    # that recorded the train phases (a custom-role harness user still
    # gets a report); shares are derived once per process here and
    # reused by the report loop
    trainers = [
        (p, shares)
        for p in procs
        for shares in [fleet.train_phase_shares(p)]
        if p.role == args.role or shares is not None
    ]
    if not trainers:
        emit({
            "event": "error", "path": args.spool_dir,
            "error": (
                f"no trainer spools found ({len(procs)} spool files, "
                f"roles: {sorted({p.role for p in procs})})"
                if procs else "no spool files found"
            ),
        })
        return 2
    now = agg._clock()
    merged_step = Histogram()
    fleet_phase_seconds: Dict[str, float] = {}
    fleet_steps = 0
    for p, shares in trainers:
        steps = p.counters.get("train.steps", 0)
        fleet_steps += steps
        wall = p.heartbeat - p.created if p.created else 0.0
        phase_seconds = {
            phase: round(p.stages[telemetry.TRAIN_STAGE_PREFIX + phase][3], 6)
            for phase in telemetry.TRAIN_PHASES
            if telemetry.TRAIN_STAGE_PREFIX + phase in p.stages
        }
        for phase, s in phase_seconds.items():
            fleet_phase_seconds[phase] = fleet_phase_seconds.get(phase, 0.0) + s
        line: Dict = {
            "event": "trainer",
            "host": p.host,
            "pid": p.pid,
            "role": p.role,
            "alive": id(p) not in dead_ids,
            **({"finished": True} if p.final else {}),
            "heartbeat_age_s": round(p.heartbeat_age(now), 3),
            "steps": steps,
            "steps_per_sec": (
                round(steps / wall, 3) if steps and wall > 0 else None
            ),
            "phase_shares": (
                {k: round(v, 4) for k, v in shares.items()}
                if shares else None
            ),
            "phase_seconds": phase_seconds,
            "verdict": telemetry.training_verdict(shares),
        }
        step_state = p.hists.get("train.step")
        if step_state:
            try:
                h = Histogram.from_states([step_state])
                merged_step.merge_state(step_state)
                q = h.quantiles()
                line["step_p50_ms"] = round(q["p50_s"] * 1e3, 3)
                line["step_p99_ms"] = round(q["p99_s"] * 1e3, 3)
            except (ValueError, TypeError, KeyError, IndexError):
                pass  # one trainer's corrupt hist loses its quantiles only
        # in-jit model diagnostics, when the trainer folded them
        moe = {
            k.split(".", 1)[1]: v
            for k, v in p.gauges.items() if k.startswith("moe.")
        }
        if moe:
            line["moe"] = {k: round(v, 4) for k, v in sorted(moe.items())}
        bubble = p.gauges.get("pipeline.bubble_fraction")
        if bubble is not None:
            line["pipeline_bubble_fraction"] = round(bubble, 4)
        # the mesh shape gauges (examples/_harness.report_mesh): which
        # parallelism layout this trainer is flying
        mesh_shape = {
            k[len("train.mesh."):]: int(v)
            for k, v in p.gauges.items() if k.startswith("train.mesh.")
        }
        if mesh_shape:
            line["mesh"] = dict(sorted(mesh_shape.items()))
        if p.skipped_lines:
            line["skipped_lines"] = p.skipped_lines
        emit(line)
    total_phase = sum(fleet_phase_seconds.values())
    fleet_shares = (
        {k: round(v / total_phase, 4) for k, v in fleet_phase_seconds.items()}
        if total_phase > 0 else None
    )
    summary: Dict = {
        "event": "train",
        "path": args.spool_dir,
        "trainers": len(trainers),
        "steps": fleet_steps,
        "phase_shares": fleet_shares,
        "verdict": telemetry.training_verdict(fleet_shares),
        "trace_ids": sorted(
            {p.trace_id for p, _ in trainers if p.trace_id}
        ),
    }
    if merged_step.count:
        q = merged_step.quantiles()
        summary["step_p50_ms"] = round(q["p50_s"] * 1e3, 3)
        summary["step_p99_ms"] = round(q["p99_s"] * 1e3, 3)
    emit(summary)
    return 0


def serve_main(argv: List[str]) -> int:
    """The ``serve`` subcommand: the serving-tier doctor. Reads the same
    telemetry spool directory as ``fleet`` but explains the SERVING tier:
    one ``{"event": "replica", ...}`` line per serving replica (request
    latency p50/p99, admission queue depth, in-flight slots, shed counts,
    per-replica SLO verdict) and one final ``{"event": "serve", ...}``
    summary (exact merged latency quantiles, fleet shed totals, the SLO
    verdict: ``meeting_slo`` / ``queue_bound`` / ``compute_bound``).
    Exit 0 = report produced (an overloaded tier is a finding, not a
    failure); 2 = unreadable spool dir or no serving spools in it."""
    ap = argparse.ArgumentParser(
        prog="tfrecord_doctor serve",
        description="Serving doctor: latency SLO verdict for the "
        "continuous-batching tier",
    )
    ap.add_argument("spool_dir", help="telemetry spool directory")
    ap.add_argument(
        "--stale-after", type=float, default=None, metavar="SECONDS",
        help="heartbeat age beyond which a replica is dead "
        "(default: 2x each process's own snapshot interval)",
    )
    ap.add_argument(
        "--trace-id", default=None, metavar="ID",
        help="only read spool files from this run",
    )
    ap.add_argument(
        "--role", default="serving", metavar="ROLE",
        help="telemetry role that marks a serving replica (default: "
        "serving); processes with serve.ticks recorded qualify regardless",
    )
    ap.add_argument(
        "--slo-ms", type=float, default=250.0, metavar="MS",
        help="p99 latency target the verdict is judged against "
        "(default: 250, the ServePolicy default)",
    )
    ap.add_argument(
        "--max-queue", type=int, default=16, metavar="N",
        help="per-replica admission queue bound used to call a replica "
        "queue_bound (default: 16, the ServePolicy default)",
    )
    _add_json_flag(ap)
    args = ap.parse_args(argv)

    emit = _Emitter(args.json)
    try:
        return _serve_report(args, emit)
    finally:
        emit.close()


# per-replica verdict ranking for the fleet line: the fleet is as sick as
# its sickest replica, and queue_bound (shedding work) outranks
# compute_bound (slow but keeping up) — same ordering ServingScaler uses
_SERVE_VERDICT_RANK = {"meeting_slo": 1, "compute_bound": 2, "queue_bound": 3}


def _serve_report(args, emit) -> int:
    from tpu_tfrecord import fleet, telemetry
    from tpu_tfrecord.telemetry import Histogram

    try:
        agg = fleet.TelemetryAggregator(
            args.spool_dir, stale_after_s=args.stale_after,
            trace_id=args.trace_id,
        )
        snap = agg.aggregate()
    except Exception as e:  # graftlint: swallow(error event emitted + exit 2)
        emit({"event": "error", "path": args.spool_dir, "error": str(e)})
        return 2
    procs = snap.processes
    dead_ids = {id(p) for p in snap.dead}
    # a replica is anything stamped with the serving role OR anything that
    # recorded scheduler ticks (a custom-role embedder still gets judged)
    replicas = [
        p for p in procs
        if p.role == args.role or "serve.ticks" in p.counters
    ]
    if not replicas:
        emit({
            "event": "error", "path": args.spool_dir,
            "error": (
                f"no serving spools found ({len(procs)} spool files, "
                f"roles: {sorted({p.role for p in procs})})"
                if procs else "no spool files found"
            ),
        })
        return 2
    now = agg._clock()
    merged_latency = Histogram()
    fleet_queue = 0.0
    shed_total = {"rejected": 0, "deadline_expired": 0, "disconnects": 0}
    worst = "unknown"
    for p in replicas:
        queue_depth = p.gauges.get("serve.queue_depth", 0.0)
        fleet_queue += queue_depth
        sheds = {
            k: p.counters.get("serve." + k, 0)
            for k in ("rejected", "deadline_expired", "disconnects")
        }
        for k, v in sheds.items():
            shed_total[k] += v
        wall = p.heartbeat - p.created if p.created else 0.0
        completed = p.counters.get("serve.requests", 0)
        line: Dict = {
            "event": "replica",
            "host": p.host,
            "pid": p.pid,
            "role": p.role,
            "alive": id(p) not in dead_ids,
            **({"finished": True} if p.final else {}),
            "heartbeat_age_s": round(p.heartbeat_age(now), 3),
            "requests": completed,
            "requests_per_sec": (
                round(completed / wall, 3) if completed and wall > 0 else None
            ),
            "queue_depth": round(queue_depth, 1),
            "in_flight": round(p.gauges.get("serve.in_flight", 0.0), 1),
            "sheds": sheds,
        }
        p99_ms = None
        lat_state = p.hists.get("serve.latency")
        if lat_state:
            try:
                h = Histogram.from_states([lat_state])
                merged_latency.merge_state(lat_state)
                q = h.quantiles()
                line["latency_p50_ms"] = round(q["p50_s"] * 1e3, 3)
                p99_ms = round(q["p99_s"] * 1e3, 3)
                line["latency_p99_ms"] = p99_ms
            except (ValueError, TypeError, KeyError, IndexError):
                pass  # one replica's corrupt hist loses its quantiles only
        verdict = telemetry.serving_verdict(
            p99_ms, queue_depth, args.slo_ms, max_queue=args.max_queue,
        )
        line["verdict"] = verdict
        if p.skipped_lines:
            line["skipped_lines"] = p.skipped_lines
        emit(line)
        if _SERVE_VERDICT_RANK.get(verdict, 0) > _SERVE_VERDICT_RANK.get(
            worst, 0
        ):
            worst = verdict
    summary: Dict = {
        "event": "serve",
        "path": args.spool_dir,
        "replicas": len(replicas),
        "requests": sum(p.counters.get("serve.requests", 0) for p in replicas),
        "queue_depth": round(fleet_queue, 1),
        "sheds": shed_total,
        "slo_p99_ms": args.slo_ms,
        "verdict": worst,
        "trace_ids": sorted({p.trace_id for p in replicas if p.trace_id}),
    }
    if merged_latency.count:
        q = merged_latency.quantiles()
        summary["latency_p50_ms"] = round(q["p50_s"] * 1e3, 3)
        summary["latency_p99_ms"] = round(q["p99_s"] * 1e3, 3)
        # "p99 exemplar: trace=… span=…" — the clickable pointer from the
        # fleet tail back to the request trace that filled it
        ex = merged_latency.exemplar_at(0.99)
        if ex is not None:
            summary["p99_exemplar"] = {
                "trace": ex["trace_id"],
                "span": ex["span_id"],
                "value_ms": round(ex["value"] * 1e3, 3),
            }
    # error-budget state rides ADDITIVE summary fields: the point-p99
    # "verdict" keeps its pinned value set, "error_budget" upgrades it to
    # budget-remaining + burn-rate terms (tpu_tfrecord.slo) computed from
    # the spool's full history against the same --slo-ms target
    from tpu_tfrecord import slo as _slo

    try:
        engine = _slo.engine_from_spool(
            args.spool_dir,
            objectives=(
                _slo.Objective(kind="availability", target=0.999),
                _slo.Objective(
                    kind="latency", target=0.95, latency_ms=args.slo_ms
                ),
            ),
            trace_id=args.trace_id,
            clock=agg._clock,
        )
    except OSError:
        engine = None
    if engine is not None:
        budget = engine.evaluate(now)
        summary["error_budget"] = {
            "verdict": budget["verdict"],
            "objectives": {
                e["objective"]: {
                    "budget_remaining": round(e["budget_remaining"], 4),
                    "verdict": e["verdict"],
                }
                for e in budget["objectives"]
            },
        }
    emit(summary)
    return 0


def slo_main(argv: List[str]) -> int:
    """The ``slo`` subcommand: the error-budget doctor. Replays a spool
    directory's cumulative history into tpu_tfrecord.slo's multi-window
    multi-burn-rate engine: one ``{"event": "objective", ...}`` line per
    declared objective (budget remaining, fast/slow window burn rates,
    per-objective verdict) and one final ``{"event": "slo", ...}`` line
    with the worst verdict (``healthy`` / ``slow_burn`` / ``fast_burn``).
    Exit 0 = report produced (a burning budget is a finding, not a
    failure); 2 = unreadable spool dir, bad objective spec, or no spool
    snapshots."""
    ap = argparse.ArgumentParser(
        prog="tfrecord_doctor slo",
        description="Error-budget doctor: multi-window multi-burn-rate "
        "SLO verdict from a telemetry spool directory",
    )
    ap.add_argument("spool_dir", help="telemetry spool directory")
    ap.add_argument(
        "--objective", action="append", default=None, metavar="SPEC",
        help="objective spec, repeatable: availability:TARGET or "
        "latency:TARGET:MS (default: availability:0.999 and "
        "latency:0.95:250)",
    )
    ap.add_argument(
        "--trace-id", default=None, metavar="ID",
        help="only read spool files from this run",
    )
    ap.add_argument(
        "--window-scale", type=float, default=1.0, metavar="X",
        help="multiply every burn-window length by X (tests shrink the "
        "1h/5m + 6h/30m defaults to fake-clock scale; thresholds are "
        "untouched)",
    )
    ap.add_argument(
        "--now", type=float, default=None, metavar="UNIX_TS",
        help="evaluate as of this wall-clock time instead of now "
        "(deterministic replays of an archived spool)",
    )
    _add_json_flag(ap)
    args = ap.parse_args(argv)

    emit = _Emitter(args.json)
    try:
        return _slo_report(args, emit)
    finally:
        emit.close()


def _slo_report(args, emit) -> int:
    from tpu_tfrecord import slo as _slo

    try:
        objectives = (
            tuple(_slo.Objective.parse(s) for s in args.objective)
            if args.objective
            else _slo.DEFAULT_OBJECTIVES
        )
    except ValueError as e:
        emit({"event": "error", "error": str(e)})
        return 2
    windows = tuple(
        w.scaled(args.window_scale) for w in _slo.DEFAULT_WINDOWS
    )
    try:
        engine = _slo.engine_from_spool(
            args.spool_dir,
            objectives=objectives,
            windows=windows,
            trace_id=args.trace_id,
        )
    except OSError as e:
        emit({"event": "error", "path": args.spool_dir, "error": str(e)})
        return 2
    if engine is None:
        emit({
            "event": "error", "path": args.spool_dir,
            "error": "no spool snapshots found",
        })
        return 2
    report = engine.evaluate(args.now)
    for entry in report["objectives"]:
        emit({
            "event": "objective",
            "objective": entry["objective"],
            "kind": entry["kind"],
            "target": entry["target"],
            "bad": entry["bad"],
            "total": entry["total"],
            "budget_remaining": round(entry["budget_remaining"], 4),
            "windows": [
                {
                    "name": w["name"],
                    "threshold": w["threshold"],
                    "long_burn": round(w["long_burn"], 3),
                    "short_burn": round(w["short_burn"], 3),
                    "alerting": w["alerting"],
                }
                for w in entry["windows"]
            ],
            "verdict": entry["verdict"],
        })
    emit({
        "event": "slo",
        "path": args.spool_dir,
        "objectives": [o.spec for o in objectives],
        "verdict": report["verdict"],
    })
    return 0


def merge_trace_main(argv: List[str]) -> int:
    """The ``merge-trace`` subcommand: fuse per-process Chrome traces into
    one Perfetto timeline. Exit 0 = merged; 2 = unreadable/malformed input."""
    ap = argparse.ArgumentParser(
        prog="tfrecord_doctor merge-trace",
        description="Fuse per-process Chrome trace files into one "
        "pid-labeled Perfetto timeline",
    )
    ap.add_argument("out", help="merged trace output path")
    ap.add_argument(
        "traces", nargs="+",
        help="per-process trace JSON files; a directory stands for every "
        "*.json inside it, sorted",
    )
    args = ap.parse_args(argv)

    from tpu_tfrecord import telemetry

    def emit(obj: Dict) -> None:
        sys.stdout.write(json.dumps(obj, sort_keys=True) + "\n")

    traces: List[str] = []
    for path in args.traces:
        if os.path.isdir(path):
            inside = sorted(
                os.path.join(path, name)
                for name in os.listdir(path)
                if name.endswith(".json")
            )
            if not inside:
                emit({
                    "event": "error", "path": path,
                    "error": "directory holds no *.json traces",
                })
                return 2
            traces.extend(inside)
        else:
            traces.append(path)

    try:
        merged = telemetry.merge_chrome_traces(args.out, traces)
    except (OSError, ValueError) as e:
        emit({"event": "error", "path": args.out, "error": str(e)})
        return 2
    pids = {
        e.get("pid") for e in merged["traceEvents"] if e.get("pid") is not None
    }
    emit(
        {
            "event": "merged_trace",
            "path": args.out,
            "inputs": len(traces),
            "pids": len(pids),
            "events": len(merged["traceEvents"]),
        }
    )
    return 0


def lint_main(argv: List[str]) -> int:
    """The ``lint`` subcommand: run the graftlint invariant suite
    (tools/graftlint — clock/atomic-write/lock/except/vocabulary rules,
    plus the HLO collective contracts under ``--hlo``) doctor-shaped: one
    ``finding`` event per non-baselined violation, ``stale_baseline``
    warnings, ``hlo_contract`` rows, and a final ``lint`` summary. Exit
    0 = clean; 1 = findings (or a failed HLO contract); 2 = an input
    could not be read/parsed."""
    ap = argparse.ArgumentParser(
        prog="tfrecord_doctor lint",
        description="Run the repo's AST + HLO invariant checker "
        "(tools/graftlint)",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: tpu_tfrecord tools examples)",
    )
    ap.add_argument(
        "--baseline", default=None, metavar="PATH",
        help="baseline of grandfathered finding keys "
        "(default: tools/graftlint/baseline.txt)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, including baselined ones",
    )
    ap.add_argument(
        "--hlo", action="store_true",
        help="also compile and check the HLO collective contracts (slow)",
    )
    _add_json_flag(ap)
    args = ap.parse_args(argv)

    emit = _Emitter(args.json)
    try:
        return _lint_report(args, emit)
    finally:
        emit.close()


def _lint_report(args, emit) -> int:
    from tools.graftlint import DEFAULT_BASELINE, run_lint

    baseline = None if args.no_baseline else (args.baseline or DEFAULT_BASELINE)
    try:
        result = run_lint(
            paths=args.paths or None, baseline=baseline, hlo=args.hlo
        )
    except FileNotFoundError as e:
        emit({"event": "error", "error": str(e)})
        return 2
    for f in result["findings"]:
        emit(f.to_json())
    for key in result["stale_baseline"]:
        emit({"event": "stale_baseline", "key": key})
    for err in result["errors"]:
        emit({"event": "error", "error": err})
    for entry in result["hlo"]:
        emit({"event": "hlo_contract", **entry})
    hlo_failed = [
        e for e in result["hlo"] if not e["ok"] and not e["skipped"]
    ]
    emit(
        {
            "event": "lint",
            "findings": len(result["findings"]),
            "baselined": result["baselined"],
            "stale_baseline": len(result["stale_baseline"]),
            "errors": len(result["errors"]),
            "hlo_checked": len(result["hlo"]),
            "hlo_failed": len(hlo_failed),
        }
    )
    if result["errors"]:
        return 2
    return 1 if (result["findings"] or hlo_failed) else 0


def main(argv: Optional[List[str]] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "cache":
        return cache_main(argv[1:])
    if argv and argv[0] == "report":
        return report_main(argv[1:])
    if argv and argv[0] == "tune":
        return tune_main(argv[1:])
    if argv and argv[0] == "fleet":
        return fleet_main(argv[1:])
    if argv and argv[0] == "train":
        return train_main(argv[1:])
    if argv and argv[0] == "serve":
        return serve_main(argv[1:])
    if argv and argv[0] == "slo":
        return slo_main(argv[1:])
    if argv and argv[0] == "serve-status":
        return serve_status_main(argv[1:])
    if argv and argv[0] == "merge-trace":
        return merge_trace_main(argv[1:])
    if argv and argv[0] == "lint":
        return lint_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="tfrecord_doctor", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="+", help="shard files, dirs, or globs")
    ap.add_argument(
        "--repair", action="store_true",
        help="write a .repaired copy keeping every valid record",
    )
    ap.add_argument(
        "--out", default=None,
        help="explicit output path for --repair (single input file only)",
    )
    ap.add_argument(
        "--max-record-bytes", type=int, default=1 << 30,
        help="declared lengths beyond this are treated as corrupt (default 1 GiB)",
    )
    ap.add_argument(
        "--simulate", default=None, metavar="PLAN_JSON",
        help="replay a FaultPlan JSON (tpu_tfrecord.faults) against the "
        "scan and report its fault ledger — deterministic chaos repro",
    )
    args = ap.parse_args(argv)

    def emit(obj: Dict) -> None:
        sys.stdout.write(json.dumps(obj, sort_keys=True) + "\n")

    import contextlib

    chaos = contextlib.nullcontext()
    plan = None
    if args.simulate is not None:
        from tpu_tfrecord.faults import FaultPlan, install_chaos

        try:
            with open(args.simulate) as fh:
                plan = FaultPlan.from_json(json.load(fh))
        except (OSError, ValueError) as e:  # missing/bad JSON, bad rule
            emit({"event": "error", "path": args.simulate,
                  "error": f"unreadable fault plan: {e}"})
            return 2
        chaos = install_chaos(plan)

    try:
        with chaos:
            try:
                files = expand_paths(args.paths)
            except (OSError, ValueError) as e:
                emit({"event": "error", "error": str(e)})
                return 2
            if args.out is not None and len(files) != 1:
                ap.error("--out requires exactly one input file")
            if args.repair and args.out is None:
                from tpu_tfrecord import fs as _fs

                remote = [p for p in files if _fs.has_scheme(p)]
                if remote:
                    ap.error(
                        "--repair of a remote source needs an explicit "
                        f"LOCAL --out (cannot write next to {remote[0]})"
                    )
            rc = 0
            for path in files:
                try:
                    summary = doctor_file(
                        path, args.repair, args.out, args.max_record_bytes, emit
                    )
                except Exception as e:  # unreadable file, not corrupt frames  # graftlint: swallow(error event emitted per file; rc=2)
                    emit({"event": "error", "path": path, "error": str(e)})
                    rc = 2
                    continue
                if summary["corrupt_events"] and rc == 0:
                    rc = 1
    finally:
        # the ledger IS the repro report: emit it on every exit path,
        # including a failed path expansion (possibly failed by the plan's
        # own injected listdir fault)
        if plan is not None:
            for entry in plan.ledger:
                emit({"event": "fault", **entry})
    return rc


if __name__ == "__main__":
    sys.exit(main())
