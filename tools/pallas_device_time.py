#!/usr/bin/env python
"""Device-time comparison: Pallas dot-interaction vs XLA reference.

Wall-clock through the axon tunnel is dominated by dispatch latency
(~2.4 ms observed), and naive K-iteration Python loops let XLA hoist or
CSE the repeated op (PARITY.md: earlier isolation attempts "collapse
under XLA's loop optimizations"). This tool measures honestly:

- K applications run inside ONE jit via ``lax.fori_loop``;
- each iteration's input depends on the previous output through a scalar
  carry (``emb * (1 + eps * out.mean())``), so iterations can neither be
  hoisted, CSE'd, nor reordered — the loop body must execute K times;
- per-iteration overhead of the carry is one reduction + one broadcast
  multiply, identical for both implementations, so it cancels in the
  ratio;
- the measured quantity is (t_loop(K2) - t_loop(K1)) / (K2 - K1):
  subtracting two loop lengths cancels dispatch AND warmup entirely.

Run on a real TPU: ``python tools/pallas_device_time.py``. Prints a
markdown table (for PARITY.md) plus one JSON line per shape.

On CPU it falls back to interpret=True for the Pallas path — only useful
as a smoke test of the harness itself, never as evidence.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from tpu_tfrecord.models.interaction import (
    dot_interaction_pallas,
    dot_interaction_reference,
)

K1 = int(os.environ.get("TFR_PALLAS_K1", 20))
K2 = int(os.environ.get("TFR_PALLAS_K2", 120))
REPEATS = int(os.environ.get("TFR_PALLAS_REPEATS", 5))


def _looped(fn, k: int):
    """K data-dependent applications of fn inside one jit."""

    @jax.jit
    def run(emb):
        def body(_, carry):
            emb, acc = carry
            out = fn(emb)
            m = out.astype(jnp.float32).mean()
            # scalar feedback: next input depends on this output, so the
            # loop body cannot be hoisted or collapsed; eps keeps values
            # numerically unchanged in bf16
            emb = emb * (1 + 1e-12 * m).astype(emb.dtype)
            return emb, acc + m

        _, acc = jax.lax.fori_loop(0, k, body, (emb, jnp.float32(0)))
        return acc

    return run


def _time_loop(run, emb) -> float:
    # Completion is forced with a SCALAR FETCH of the loop's f32 accumulator,
    # not block_until_ready: on this tunneled client block_until_ready
    # returns before the computation actually finishes (bench.py observed a
    # chain of twenty 4096^2 matmuls "complete" in ~0ms; the 4-byte d2h
    # fetch waits for true execution). The fetch's round-trip latency is a
    # constant per timing, so the two-length delta cancels it exactly like
    # dispatch.
    float(run(emb))  # compile + warm
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        float(run(emb))
        best = min(best, time.perf_counter() - t0)
    return best


def measure(fn, emb) -> float:
    """Per-application device time in seconds via the two-length delta.
    Raises on a non-monotonic measurement (t_K2 <= t_K1): that means noise
    swamped the op — exactly the bogus number this tool must never emit.
    Raise K2 (TFR_PALLAS_K2) until the delta is stable."""
    t1 = _time_loop(_looped(fn, K1), emb)
    t2 = _time_loop(_looped(fn, K2), emb)
    if t2 <= t1:
        raise RuntimeError(
            f"non-monotonic timing: t(K={K2})={t2:.6f}s <= t(K={K1})={t1:.6f}s"
            " — noise exceeds the op cost; raise TFR_PALLAS_K2/REPEATS"
        )
    return (t2 - t1) / (K2 - K1)


def main() -> None:
    backend = jax.default_backend()
    interpret = backend != "tpu"
    if interpret:
        print(f"# WARNING: backend={backend}; Pallas runs in interpret mode "
              "— harness smoke test only, NOT evidence", file=sys.stderr)
    b = int(os.environ.get("TFR_PALLAS_B", 8192))
    d = int(os.environ.get("TFR_PALLAS_D", 32))
    shapes = [int(f) for f in os.environ.get(
        "TFR_PALLAS_FS", "8,16,27,32,64").split(",")]
    rng = np.random.default_rng(0)
    print(f"| F | P | XLA µs | Pallas µs | Pallas speedup | (B={b}, D={d}, "
          f"bf16, {backend}) |")
    print("|---|---|--------|-----------|----------------|---|")
    for f in shapes:
        emb = jnp.asarray(rng.normal(size=(b, f, d)), dtype=jnp.bfloat16)
        t_xla = measure(dot_interaction_reference, emb)
        t_pallas = measure(
            functools.partial(dot_interaction_pallas, interpret=interpret), emb
        )
        ratio = t_xla / t_pallas
        p = f * (f - 1) // 2
        print(f"| {f} | {p} | {t_xla * 1e6:.1f} | {t_pallas * 1e6:.1f} "
              f"| {ratio:.2f}x | |")
        print(json.dumps({
            "metric": "dot_interaction_device_time",
            "backend": backend, "B": b, "F": f, "D": d,
            "xla_us": round(t_xla * 1e6, 2),
            "pallas_us": round(t_pallas * 1e6, 2),
            "pallas_speedup": round(ratio, 3),
            "interpret": interpret,
        }), file=sys.stderr)


if __name__ == "__main__":
    main()
