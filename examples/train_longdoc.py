#!/usr/bin/env python
"""End-to-end example: train the long-document classifier on SequenceExamples.

The long-context twin of examples/train_dlrm.py — covers the ragged path of
the framework surface:
  1. generate ragged SequenceExample documents (variable-length FeatureLists)
  2. stream them with TFRecordDataset (recordType=SequenceExample)
  3. pad/bucket frames to dense [B, L, D] + lengths, assemble seq-sharded
     global batches over a dp x sp mesh
  4. jit train steps whose attention runs as RING ATTENTION over the 'seq'
     axis; checkpoint the input position
  5. resume from the saved state (identity-fingerprinted)

Run on any JAX backend; for a local simulation:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_longdoc.py
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import tpu_tfrecord

# Without this, a dead device tunnel makes backend discovery hang even
# under JAX_PLATFORMS=cpu — see ensure_jax_platform.
tpu_tfrecord.ensure_jax_platform()

import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _harness

import tpu_tfrecord.io as tfio
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.models import long_doc
from tpu_tfrecord.schema import (
    ArrayType,
    FloatType,
    LongType,
    StructField,
    StructType,
)
from tpu_tfrecord.tpu import make_global_batch
from tpu_tfrecord.tpu.mesh import create_mesh

SEQ_DIM = 16
MAX_LEN = 64
BATCH = 64


def make_schema() -> StructType:
    return StructType(
        [
            StructField("label", LongType(), nullable=False),
            StructField("frames", ArrayType(ArrayType(FloatType()))),
        ]
    )


def generate(data_dir: str, shards: int = 4, rows: int = 256) -> None:
    """Ragged documents whose label depends on the (variable-length)
    content, written through the io layer as SequenceExamples. ONE write
    job (sharded via max_records_per_file) so _SUCCESS appears only after
    ALL shards committed — a kill mid-generation can never leave a
    marker over a partial dataset."""
    if os.path.exists(os.path.join(data_dir, "_SUCCESS")):
        return
    rng = np.random.default_rng(0)
    schema = make_schema()
    all_rows = []
    for _ in range(shards * rows):
        n = int(rng.integers(4, MAX_LEN + 1))
        frames = rng.normal(size=(n, SEQ_DIM))
        label = int(frames[:, 0].mean() > 0)
        all_rows.append([label, [[float(x) for x in row] for row in frames]])
    from tpu_tfrecord.io.writer import DatasetWriter
    from tpu_tfrecord.options import TFRecordOptions

    writer = DatasetWriter(
        data_dir,
        schema,
        TFRecordOptions.from_map(recordType="SequenceExample"),
        mode="overwrite",
        max_records_per_file=rows,
    )
    writer.write_rows(all_rows)


def main() -> None:
    data_dir = "/tmp/tpu_tfrecord_longdoc/data"
    ckpt_dir = "/tmp/tpu_tfrecord_longdoc/ckpt"
    generate(data_dir)
    schema = make_schema()

    # Pick (data, seq) such that the batch divides the data axis and the
    # padded length divides the seq axis — any device count works (odd
    # counts fall back to data=1).
    n_dev = len(jax.devices())
    for seq in (4, 2, 1):
        if n_dev % seq == 0 and BATCH % (n_dev // seq) == 0 and MAX_LEN % seq == 0:
            data = n_dev // seq
            break
    else:
        data, seq = 1, 1
    mesh = create_mesh({"data": data, "seq": seq}, jax.devices()[: data * seq])
    cfg = long_doc.LongDocConfig(
        seq_dim=SEQ_DIM, d_model=32, n_heads=4, n_layers=2, max_len=MAX_LEN,
        # 'ring' (default) or 'ulysses' — n_heads=4 covers every seq size
        # the picker above can choose, so both flavors run on any device
        # count (LONGDOC_SP_ATTENTION=ulysses to exercise the all-to-all SP)
        sp_attention=os.environ.get("LONGDOC_SP_ATTENTION", "ring"),
        # LONGDOC_MOE_EXPERTS=4 swaps the FFN for the Switch MoE layer
        moe_experts=int(os.environ.get("LONGDOC_MOE_EXPERTS", "0")),
        # LONGDOC_KV_HEADS=2 runs GQA (k/v carry fewer heads; with
        # sp_attention=ulysses it must divide the seq-axis size too)
        n_kv_heads=int(os.environ.get("LONGDOC_KV_HEADS", "0")),
    )
    params = long_doc.init_params(jax.random.key(0), cfg)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    step_fn = jax.jit(
        functools.partial(
            long_doc.train_step, cfg=cfg, tx=tx, mesh=mesh, data_axis="data"
        ),
        donate_argnums=(0, 1),
    )

    ds = TFRecordDataset(
        data_dir, batch_size=BATCH, schema=schema, num_epochs=2,
        recordType="SequenceExample", shuffle=True, seed=0,
    )
    import ml_dtypes

    from tpu_tfrecord.tpu import host_batch_from_columnar

    shardings = {}  # computed once; frames carries the (data, seq) spec

    def produce(cb):
        # pad + f32->bf16 fused in the native kernel: frames arrive in the
        # model's compute dtype at half the link bytes, with no host-side
        # f32 dense batch
        hb = host_batch_from_columnar(
            cb, ds.schema, pad_to={"frames": (MAX_LEN, SEQ_DIM)},
            cast={"frames": ml_dtypes.bfloat16},
        )
        hb.pop("frames_inner_len")
        if not shardings:
            shardings.update(long_doc.batch_shardings(mesh, hb))
        return make_global_batch(hb, mesh, shardings=shardings)

    def step(state, gb):
        params, opt_state = state
        params, opt_state, loss = step_fn(params, opt_state, gb)
        return (params, opt_state), loss

    # TFR_TRAIN_SPOOL_DIR spools this trainer (role=trainer) for the
    # fleet doctor; the step-phase recorder runs regardless
    spool = _harness.trainer_spool()
    phases = _harness.StepPhases()
    t0 = time.perf_counter()
    it, _resume = _harness.resume_or_fresh(ds, ckpt_dir)
    save_cb, saver = _harness.state_saver(ckpt_dir)
    try:
        with it:
            (params, opt_state), steps, duty = _harness.run_train_loop(
                it, produce, step, (params, opt_state),
                save=save_cb,
                phases=phases,
            )
        saver.wait()  # drain the background commit before the summary
        _harness.finish(ckpt_dir, steps, BATCH, t0, duty, phases=phases)
    finally:
        saver.close()
        _harness.release_trainer_spool(spool)


if __name__ == "__main__":
    main()
