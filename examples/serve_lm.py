#!/usr/bin/env python
"""End-to-end example: SERVE the causal LM trained by examples/train_lm.py
through the microbatch-streamed pipeline (ISSUE 15 / ROADMAP #2).

The inference twin of the trainer: load the trainer's atomic checkpoint
(the ONE [n_layers, ...]-stacked block pytree every mesh shares), restack
it into S×V interleaved pipeline chunks, and answer requests one
[mb, L+1] microbatch at a time through `models.lm.LMStream`:

  - the per-call feed is exactly ONE microbatch slice riding the pipeline
    feed ring — no request stream is ever materialized (the compiled
    step's argument bytes are the pin, tests/test_pipeline_stream.py)
  - streamed logits are BITWISE equal to the batch path (`pipeline_apply`
    on the same slices) — checked here on every run, so the serving
    surface cannot drift from the trained graph
  - requests/s and per-request latency are measured and reported, and the
    `serve.requests` counter / `serve.latency` histogram feed the flight
    recorder like every other stage

With ``--serve`` the example becomes a long-running serving REPLICA over
the same checkpoint: the continuous-batching tier (tpu_tfrecord.serving)
multiplexes concurrent socket clients onto the one compiled per-tick
step, with admission control, per-request deadlines, and graceful drain —
SIGTERM/SIGINT stops admitting, finishes every in-flight request, lands
the telemetry spool's ``final: true`` snapshot, and exits 0. Read the
replica with ``tools/tfrecord_doctor.py serve SPOOL_DIR``.

The checkpoint read routes through the manifest-last restore path
(``load_checkpoint`` below) in BOTH modes: a generation the trainer is
still committing in the background has no manifest yet and is invisible,
so serving can never half-read it.

Run on any JAX backend; for a local simulation (after train_lm):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_lm.py --mesh dp_pp --steps 8
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/serve_lm.py --pipe 2 --virtual 2
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/serve_lm.py --serve --spool-dir /tmp/serve_spool
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import tpu_tfrecord

# Without this, a dead device tunnel makes backend discovery hang even
# under JAX_PLATFORMS=cpu — see ensure_jax_platform.
tpu_tfrecord.ensure_jax_platform()

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from train_lm import BATCH, SEQ_LEN, VOCAB, LMCheckpoint  # noqa: E402  (the trainer owns the model constants)

from tpu_tfrecord.metrics import METRICS  # noqa: E402
from tpu_tfrecord.models import lm  # noqa: E402
from tpu_tfrecord.tpu import create_mesh  # noqa: E402

# the dp_pp trainer's depth (train_lm.pick_mesh): the checkpoint this
# example loads carries 4 stacked blocks
N_LAYERS = 4


def load_checkpoint(ckpt_dir: str, template):
    """The ONE serving-side checkpoint read: route through
    ``LMCheckpoint.load`` — the manifest-last ``AsyncCheckpointer.restore``
    — never the ``gen-*/`` directory layout directly. A generation the
    trainer's background commit thread is still writing (or one a crash
    left half-written) has no ``MANIFEST.json`` yet, so it is invisible
    here and the newest COMPLETE generation is served instead; the
    serving tier can never half-read a checkpoint. Pinned with the
    checkpoint chaos park seam in tests/test_serving.py.

    Returns ``(step, state)``; ``(None, template)`` when no complete
    generation exists."""
    ck = LMCheckpoint(ckpt_dir)
    try:
        step, state, _payload = ck.load(template)
    finally:
        ck.close()
    return step, state


def serve(stream: "lm.LMStream", requests) -> dict:
    """Push every request through the stream, collecting outputs FIFO and
    per-request latency (submit -> pop). Returns outputs + timings."""
    outs, lat, submit_t = [], [], []
    t0 = time.perf_counter()

    def collect(ready):
        now = time.perf_counter()
        for o in ready:
            lat.append(now - submit_t[len(outs)])
            outs.append(o)
            METRICS.count("serve.requests")
            METRICS.observe("serve.latency", lat[-1])

    for r in requests:
        submit_t.append(time.perf_counter())
        collect(stream.submit(r))
    collect(stream.flush())
    wall = time.perf_counter() - t0
    return {"outs": outs, "latencies": lat, "wall_s": wall}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt-dir", default="/tmp/tpu_tfrecord_lm/ckpt",
                    help="train_lm's checkpoint dir (gen-*/ generations)")
    ap.add_argument("--pipe", type=int, default=2, metavar="S",
                    help="pipeline stages (devices)")
    ap.add_argument("--virtual", type=int, default=2, metavar="V",
                    help="interleaved virtual stages per device "
                         "(n_layers must divide by S*V)")
    ap.add_argument("--requests", type=int, default=32, metavar="N",
                    help="streamed microbatches to serve (timed pass)")
    ap.add_argument("--mb", type=int, default=8,
                    help="sequences per request microbatch")
    ap.add_argument("--serve", action="store_true",
                    help="run as a long-lived serving replica "
                         "(continuous batching over sockets; graceful "
                         "SIGTERM/SIGINT drain) instead of the one-shot "
                         "timed pass")
    ap.add_argument("--host", default="127.0.0.1",
                    help="--serve: bind host")
    ap.add_argument("--port", type=int, default=0,
                    help="--serve: bind port (0 = ephemeral, printed on "
                         "the ready line)")
    ap.add_argument("--max-queue", type=int, default=16,
                    help="--serve: admission queue bound (beyond it "
                         "requests are shed with a Retry-After hint)")
    ap.add_argument("--default-deadline-s", type=float, default=None,
                    help="--serve: deadline applied to requests that "
                         "carry none")
    ap.add_argument("--slo-p99-ms", type=float, default=250.0,
                    help="--serve: p99 target the status verdict is "
                         "judged against")
    ap.add_argument("--spool-dir", default=None,
                    help="--serve: telemetry spool dir (read with "
                         "tfrecord_doctor serve)")
    ap.add_argument("--role", default="serving",
                    help="--serve: telemetry role stamped on the spool")
    args = ap.parse_args()

    cfg = lm.LMConfig(
        vocab_size=VOCAB, d_model=64, n_heads=4, n_layers=N_LAYERS,
        max_len=SEQ_LEN, n_micro=BATCH // args.mb, n_virtual=args.virtual,
    )
    n_dev = len(jax.devices())
    if args.pipe > n_dev:
        ap.error(f"--pipe {args.pipe} exceeds {n_dev} devices")
    mesh = create_mesh({"pipe": args.pipe}, jax.devices()[: args.pipe])

    # the trainer's checkpoint: params + opt state from the newest
    # COMPLETE generation (manifest-last layout); the serving path wants
    # only the params half of the (params, opt) tuple
    template = lm.init_params(jax.random.key(0), cfg)
    import optax

    tx = optax.adam(3e-3)
    step, (params, _opt) = load_checkpoint(
        args.ckpt_dir, (template, tx.init(template))
    )
    if step is None:
        print(f"no complete checkpoint generation in {args.ckpt_dir} — "
              f"run train_lm first", file=sys.stderr)
        sys.exit(1)
    params = jax.tree.map(np.asarray, params)
    print(f"serving checkpoint step {step} on pipe={args.pipe} "
          f"virtual={args.virtual} mb={args.mb}",
          file=sys.stderr if args.serve else sys.stdout)

    if args.serve:
        # replica mode: the overload-proof tier over this checkpoint.
        # run_server owns the signal story — SIGTERM/SIGINT drains
        # (stop admitting, finish in-flight, final spool snapshot) and
        # returns 0; the ready line (addr + pid JSON) goes to stdout so
        # spawners/scalers can find the ephemeral port
        from tpu_tfrecord import serving

        policy = serving.ServePolicy(
            mb=args.mb, max_queue=args.max_queue,
            default_deadline_s=args.default_deadline_s,
            slo_p99_ms=args.slo_p99_ms,
        )
        engine = serving.ServingEngine(
            params, cfg, mesh, pipe_axis="pipe", policy=policy
        )
        server = serving.ServeServer(
            engine, host=args.host, port=args.port
        ).start()
        sys.exit(serving.run_server(
            server, spool_dir=args.spool_dir, role=args.role,
            ready_fh=sys.stdout,
        ))

    stream = lm.LMStream(params, cfg, mesh, pipe_axis="pipe")
    reqs = [
        lm.make_synthetic_tokens(cfg, args.mb, seed=1000 + i)
        for i in range(args.requests)
    ]

    # warmup pass: compiles the embed/head/step programs and fills the
    # pipeline once; then reset and measure a clean serve
    serve(stream, reqs[: min(len(reqs), args.pipe * args.virtual + 2)])
    stream.reset()
    res = serve(stream, reqs)
    outs, lat = res["outs"], res["latencies"]
    assert len(outs) == len(reqs), (len(outs), len(reqs))

    # the serving surface may not drift from the trained graph: streamed
    # logits must equal the batch path (batch-mode pipeline_apply over
    # the same slices) BITWISE
    ref = stream.batch_reference(reqs)
    identical = all(np.array_equal(a, b) for a, b in zip(outs, ref))
    assert identical, "streamed logits diverged from the batch path"

    line = {
        "requests": len(reqs),
        "requests_per_s": round(len(reqs) / res["wall_s"], 1),
        "sequences_per_s": round(len(reqs) * args.mb / res["wall_s"], 1),
        "latency_ms_p50": round(
            float(np.percentile(lat, 50)) * 1e3, 2
        ),
        "latency_ms_p99": round(
            float(np.percentile(lat, 99)) * 1e3, 2
        ),
        "byte_identical_to_batch": identical,
        "ckpt_step": step,
        "shape": f"mb={args.mb} L={SEQ_LEN} S={args.pipe} V={args.virtual}",
    }
    print("serve_lm OK:", json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
