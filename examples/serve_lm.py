#!/usr/bin/env python
"""End-to-end example: SERVE the causal LM trained by examples/train_lm.py
through the microbatch-streamed pipeline (ISSUE 15 / ROADMAP #2).

The inference twin of the trainer: load the trainer's atomic checkpoint
(the ONE [n_layers, ...]-stacked block pytree every mesh shares), restack
it into S×V interleaved pipeline chunks, and answer requests one
[mb, L+1] microbatch at a time through `models.lm.LMStream`:

  - the per-call feed is exactly ONE microbatch slice riding the pipeline
    feed ring — no request stream is ever materialized (the compiled
    step's argument bytes are the pin, tests/test_pipeline_stream.py)
  - streamed logits are BITWISE equal to the batch path (`pipeline_apply`
    on the same slices) — checked here on every run, so the serving
    surface cannot drift from the trained graph
  - requests/s and per-request latency are measured and reported, and the
    `serve.requests` counter / `serve.latency` histogram feed the flight
    recorder like every other stage

Run on any JAX backend; for a local simulation (after train_lm):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_lm.py --mesh dp_pp --steps 8
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/serve_lm.py --pipe 2 --virtual 2
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import tpu_tfrecord

# Without this, a dead device tunnel makes backend discovery hang even
# under JAX_PLATFORMS=cpu — see ensure_jax_platform.
tpu_tfrecord.ensure_jax_platform()

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from train_lm import BATCH, SEQ_LEN, VOCAB, LMCheckpoint  # noqa: E402  (the trainer owns the model constants)

from tpu_tfrecord.metrics import METRICS  # noqa: E402
from tpu_tfrecord.models import lm  # noqa: E402
from tpu_tfrecord.tpu import create_mesh  # noqa: E402

# the dp_pp trainer's depth (train_lm.pick_mesh): the checkpoint this
# example loads carries 4 stacked blocks
N_LAYERS = 4


def serve(stream: "lm.LMStream", requests) -> dict:
    """Push every request through the stream, collecting outputs FIFO and
    per-request latency (submit -> pop). Returns outputs + timings."""
    outs, lat, submit_t = [], [], []
    t0 = time.perf_counter()

    def collect(ready):
        now = time.perf_counter()
        for o in ready:
            lat.append(now - submit_t[len(outs)])
            outs.append(o)
            METRICS.count("serve.requests")
            METRICS.observe("serve.latency", lat[-1])

    for r in requests:
        submit_t.append(time.perf_counter())
        collect(stream.submit(r))
    collect(stream.flush())
    wall = time.perf_counter() - t0
    return {"outs": outs, "latencies": lat, "wall_s": wall}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt-dir", default="/tmp/tpu_tfrecord_lm/ckpt",
                    help="train_lm's checkpoint dir (gen-*/ generations)")
    ap.add_argument("--pipe", type=int, default=2, metavar="S",
                    help="pipeline stages (devices)")
    ap.add_argument("--virtual", type=int, default=2, metavar="V",
                    help="interleaved virtual stages per device "
                         "(n_layers must divide by S*V)")
    ap.add_argument("--requests", type=int, default=32, metavar="N",
                    help="streamed microbatches to serve (timed pass)")
    ap.add_argument("--mb", type=int, default=8,
                    help="sequences per request microbatch")
    args = ap.parse_args()

    cfg = lm.LMConfig(
        vocab_size=VOCAB, d_model=64, n_heads=4, n_layers=N_LAYERS,
        max_len=SEQ_LEN, n_micro=BATCH // args.mb, n_virtual=args.virtual,
    )
    n_dev = len(jax.devices())
    if args.pipe > n_dev:
        ap.error(f"--pipe {args.pipe} exceeds {n_dev} devices")
    mesh = create_mesh({"pipe": args.pipe}, jax.devices()[: args.pipe])

    # the trainer's checkpoint: params + opt state from the newest
    # COMPLETE generation (manifest-last layout); the serving path wants
    # only the params half of the (params, opt) tuple
    template = lm.init_params(jax.random.key(0), cfg)
    ck = LMCheckpoint(args.ckpt_dir)
    import optax

    tx = optax.adam(3e-3)
    step, (params, _opt), _payload = ck.load((template, tx.init(template)))
    ck.close()
    if step is None:
        print(f"no complete checkpoint generation in {args.ckpt_dir} — "
              f"run train_lm first", file=sys.stderr)
        sys.exit(1)
    params = jax.tree.map(np.asarray, params)
    print(f"serving checkpoint step {step} on pipe={args.pipe} "
          f"virtual={args.virtual} mb={args.mb}")

    stream = lm.LMStream(params, cfg, mesh, pipe_axis="pipe")
    reqs = [
        lm.make_synthetic_tokens(cfg, args.mb, seed=1000 + i)
        for i in range(args.requests)
    ]

    # warmup pass: compiles the embed/head/step programs and fills the
    # pipeline once; then reset and measure a clean serve
    serve(stream, reqs[: min(len(reqs), args.pipe * args.virtual + 2)])
    stream.reset()
    res = serve(stream, reqs)
    outs, lat = res["outs"], res["latencies"]
    assert len(outs) == len(reqs), (len(outs), len(reqs))

    # the serving surface may not drift from the trained graph: streamed
    # logits must equal the batch path (batch-mode pipeline_apply over
    # the same slices) BITWISE
    ref = stream.batch_reference(reqs)
    identical = all(np.array_equal(a, b) for a, b in zip(outs, ref))
    assert identical, "streamed logits diverged from the batch path"

    line = {
        "requests": len(reqs),
        "requests_per_s": round(len(reqs) / res["wall_s"], 1),
        "sequences_per_s": round(len(reqs) * args.mb / res["wall_s"], 1),
        "latency_ms_p50": round(
            float(np.percentile(lat, 50)) * 1e3, 2
        ),
        "latency_ms_p99": round(
            float(np.percentile(lat, 99)) * 1e3, 2
        ),
        "byte_identical_to_batch": identical,
        "ckpt_step": step,
        "shape": f"mb={args.mb} L={SEQ_LEN} S={args.pipe} V={args.virtual}",
    }
    print("serve_lm OK:", json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
