"""Shared train-loop harness for the examples.

The three trainers (train_dlrm, train_longdoc, train_lm) share one loop
shape: duty-cycled wait/step windows with a one-deep device pipeline
(block on step N-1's loss inside the busy window while the host prepares
batch N+1), checkpoint cadence, an end-of-run summary with the gauge-safe
stage-throughput snapshot, and fingerprint-tolerant resume. That shape
lives here ONCE; each example keeps only its data/model specifics.

Import order matters: examples run as scripts, so each one inserts the
repo root on sys.path and calls ``tpu_tfrecord.ensure_jax_platform()``
BEFORE importing this module (a dead device tunnel makes backend
discovery hang even under JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import os
import time
from typing import Callable, Iterator, Optional, Tuple

import jax

from tpu_tfrecord import checkpoint
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.tracing import DutyCycle


def resume_or_fresh(ds, ckpt_dir: str):
    """(iterator, resume_state): open ``ds.batches`` at the saved input
    position when one exists and still matches the dataset fingerprint; a
    state saved under a different dataset config starts fresh with a loud
    line rather than dying."""
    resume = checkpoint.load_state(ckpt_dir)
    print("resuming from", resume) if resume else print("fresh start")
    try:
        return ds.batches(resume), resume
    except ValueError as e:
        print(f"saved input state incompatible ({e}); starting fresh")
        return ds.batches(None), None


def stage_throughput() -> dict:
    """records/sec per pipeline stage. Gauges share the snapshot namespace
    with a distinct {"gauge": v} shape, and pure event counters ride the
    ``records`` field with ~zero seconds (their "rate" is meaningless) —
    only entries with both records AND measured time are real stages."""
    return {
        k: round(v["records_per_sec"])
        for k, v in METRICS.snapshot().items()
        if v.get("records") and v.get("seconds")
    }


def run_train_loop(
    it,
    produce: Callable,
    step_fn: Callable,
    state: Tuple,
    *,
    save: Optional[Callable[[int, object, object], None]] = None,
    save_every: int = 8,
    log_every: int = 8,
    on_step: Optional[Callable[[int, object], None]] = None,
    max_steps: Optional[int] = None,
) -> Tuple[Tuple, int, DutyCycle]:
    """The shared duty-cycled loop.

    - ``it``: the dataset's batch iterator (supports next(it, None)).
    - ``produce(cb) -> global_batch``: host prep + device placement; runs
      inside the WAIT window — it covers everything the host does between
      steps, including blocking on the prefetch queue, so the duty cycle
      cannot inflate exactly when the input pipeline is the bottleneck.
    - ``step_fn(state, gb) -> (state, loss)``: the jitted update; the
      PREVIOUS loss is blocked inside the busy window (its device time)
      and the next step dispatches async — a one-deep pipeline where host
      prep of batch N+1 overlaps device compute of batch N.
    - ``save(step, it, state)``: checkpoint cadence (every ``save_every``
      steps, aligned with the log line); receives the live train state so
      model checkpoints never need to smuggle it out of the loop.
    - ``on_step(step, loss)``: per-step hook AFTER the loss is known
      (train_lm logs step/digest/loss lines through it).

    Returns (state, steps, duty).
    """
    step = 0
    duty = DutyCycle()
    prev_loss = None
    while max_steps is None or step < max_steps:
        with duty.wait():
            cb = next(it, None)
            gb = produce(cb) if cb is not None else None
        with duty.step():
            if prev_loss is not None:
                jax.block_until_ready(prev_loss)
            if gb is not None:
                state, prev_loss = step_fn(state, gb)
        if cb is None:
            break
        step += 1
        if on_step is not None and prev_loss is not None:
            jax.block_until_ready(prev_loss)
            on_step(step, prev_loss)
        if step % log_every == 0 and prev_loss is not None:
            print(f"step {step}  loss ~{float(prev_loss):.4f}", flush=True)
        if save is not None and step % save_every == 0:
            save(step, it, state)
    if prev_loss is not None:
        jax.block_until_ready(prev_loss)
    return state, step, duty


def finish(
    ckpt_dir: Optional[str],
    step: int,
    batch_size: int,
    t0: float,
    duty: DutyCycle,
    clear_state: bool = True,
    stages: bool = False,
) -> None:
    """End-of-run bookkeeping shared by the examples: clear the input
    state when the epoch budget is exhausted (so the next run starts a
    fresh pass instead of resuming into an empty stream), print the
    examples/s line, the duty cycle, and optionally the stage table."""
    if clear_state and ckpt_dir is not None:
        state_file = checkpoint.state_path(ckpt_dir)
        if os.path.exists(state_file):
            os.remove(state_file)
    dt = time.perf_counter() - t0
    print(f"done: {step} steps, {step * batch_size / dt:,.0f} examples/s")
    if duty.value() is not None:
        print(f"device duty cycle: {duty.value():.1%}")
    if stages:
        print("stage throughput:", stage_throughput())
