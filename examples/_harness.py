"""Shared train-loop harness for the examples.

The three trainers (train_dlrm, train_longdoc, train_lm) share one loop
shape: duty-cycled wait/step windows with a one-deep device pipeline
(block on step N-1's loss inside the busy window while the host prepares
batch N+1), checkpoint cadence, an end-of-run summary with the gauge-safe
stage-throughput snapshot, and fingerprint-tolerant resume. That shape
lives here ONCE; each example keeps only its data/model specifics.

Since ISSUE 13 the loop is also the TRAINING FLIGHT RECORDER: every step
is decomposed into disjoint wall-clock phases (``train.data_wait`` /
``train.h2d`` / ``train.compute`` / ``train.ckpt`` — Metrics stages with
latency histograms, plus ``train.step`` per-step latency and a
``train.steps`` counter), each step carries a ``train.step`` span (Chrome
trace) and a ``tracing.trace`` annotation (xprof), windowed phase SHARES
land in ``train.share.<phase>`` gauges, and the windowed training verdict
(``input_bound`` / ``compute_bound`` / ``ckpt_bound`` —
telemetry.training_verdict) explains where the step went. A trainer that
spools (``trainer_spool``) is aggregated by the fleet doctor exactly like
a reader process, under the ``trainer`` role.

Import order matters: examples run as scripts, so each one inserts the
repo root on sys.path and calls ``tpu_tfrecord.ensure_jax_platform()``
BEFORE importing this module (a dead device tunnel makes backend
discovery hang even under JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Callable, Dict, Iterator, Optional, Tuple

import jax

from tpu_tfrecord import checkpoint, telemetry
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.tracing import DutyCycle, trace


def resume_or_fresh(ds, ckpt_dir: str):
    """(iterator, resume_state): open ``ds.batches`` at the saved input
    position when one exists and still matches the dataset fingerprint; a
    state saved under a different dataset config starts fresh with a loud
    line rather than dying."""
    resume = checkpoint.load_state(ckpt_dir)
    print("resuming from", resume) if resume else print("fresh start")
    try:
        return ds.batches(resume), resume
    except ValueError as e:
        print(f"saved input state incompatible ({e}); starting fresh")
        return ds.batches(None), None


def state_saver(ckpt_dir: str):
    """(save_callback, saver) for ``run_train_loop``'s ``save=`` seam.

    The callback snapshots the LIVE iterator position on the caller's
    thread and hands the fsync-then-rename write to the background commit
    thread (checkpoint.AsyncStateSaver), so the ``ckpt`` step phase
    measures microseconds instead of disk latency. ``TFR_CKPT_MODE=sync``
    keeps the write inline — the measurement twin the bench/verify
    throttle legs compare against. Callers must ``saver.close()`` in a
    ``finally`` so the last commit drains (and any commit failure
    surfaces) before the process exits."""
    sync = os.environ.get("TFR_CKPT_MODE", "async") == "sync"
    saver = checkpoint.AsyncStateSaver(ckpt_dir, sync=sync)

    def save(step, live_it, _state):
        saver.save(live_it, step=step)

    return save, saver


def stage_throughput() -> dict:
    """records/sec per pipeline stage. Gauges share the snapshot namespace
    with a distinct {"gauge": v} shape, and pure event counters ride the
    ``records`` field with ~zero seconds (their "rate" is meaningless) —
    only entries with both records AND measured time are real stages."""
    return {
        k: round(v["records_per_sec"])
        for k, v in METRICS.snapshot().items()
        if v.get("records") and v.get("seconds")
    }


class StepPhases:
    """Per-step phase decomposition: the training half of the flight
    recorder (ISSUE 13).

    Each phase is a DISJOINT wall-clock partition of one loop iteration:

    - ``data_wait``: blocked in ``next(it)`` waiting on the input
      pipeline, MINUS any transfer seconds a DeviceIterator spent
      synchronously inside that call (its ``transfer_seconds`` counter is
      snapshotted around the wait) — so H2D cost never masquerades as
      input starvation.
    - ``h2d``: host batch assembly + device placement (``produce``), plus
      the DeviceIterator transfer seconds carved out of the wait above.
    - ``compute``: the device-step window (block on step N-1's loss +
      dispatch step N).
    - ``ckpt``: the checkpoint callback.

    Phase timings are BUFFERED per step and committed by ``end_step``:
    every phase lands in the Metrics registry as a ``train.<phase>``
    stage (seconds + per-step latency histogram), each completed step
    bumps the ``train.steps`` counter, feeds the ``train.step`` per-step
    latency stage, and records one ``train.step`` flight-recorder span
    covering the step's wall extent. A partial iteration that never
    completes — the loop's final ``next(it)`` that only DISCOVERS
    exhaustion — is dropped by ``abort_step``, so stage records, window
    shares, and span counts always agree exactly with ``train.steps``
    (the drained-pipeline wait of that last probe would otherwise bias
    short runs toward input_bound). Every ``window`` steps the WINDOWED
    phase shares are published as ``train.share.<phase>`` gauges (what
    the spool ships to the fleet, and what the verdict describes — the
    recent regime, not the lifetime average) plus a ``train.verdict``
    trace instant. Overhead: a few perf_counter pairs and one locked
    Metrics add per phase per step — noise next to any real train step
    (the bench's lm_step_breakdown leg measures the loop with this on).
    """

    PHASES = telemetry.TRAIN_PHASES

    def __init__(self, window: int = 16, metrics=None):
        if window < 1:
            raise ValueError("window must be >= 1")
        self.window = int(window)
        self.metrics = metrics or METRICS
        self.steps = 0
        self._totals = {p: 0.0 for p in self.PHASES}
        self._window_start = dict(self._totals)
        self._pending = {p: 0.0 for p in self.PHASES}
        self._pending_t0_ns: Optional[int] = None
        self._last_shares: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str, iterator=None):
        """Time one phase of the current step (buffered until
        ``end_step`` commits it). ``iterator`` (the wait phase passes the
        batch iterator) lets a DeviceIterator's inline transfer seconds
        be re-attributed from data_wait to h2d."""
        if self._pending_t0_ns is None:
            self._pending_t0_ns = time.perf_counter_ns()
        t0 = time.perf_counter()
        h0 = getattr(iterator, "transfer_seconds", 0.0) if iterator is not None else 0.0
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            inline_h2d = 0.0
            if iterator is not None:
                inline_h2d = getattr(iterator, "transfer_seconds", 0.0) - h0
                # never attribute more than the wall we actually waited
                # (a transfer thread may have progressed concurrently)
                inline_h2d = min(max(0.0, inline_h2d), dt)
                dt -= inline_h2d
            self._pending[name] += dt
            self._pending["h2d"] += inline_h2d

    def end_step(self) -> None:
        """Commit the buffered phases as one completed step: stage
        totals + latency histograms, the ``train.steps`` counter, the
        ``train.step`` whole-step latency, one ``train.step`` span, and
        the windowed shares/verdict refresh every ``window`` steps."""
        step_seconds = 0.0
        for name, dt in self._pending.items():
            if dt:
                self.metrics.add(
                    telemetry.TRAIN_STAGE_PREFIX + name,
                    records=1, seconds=dt, latency=dt,
                )
                self._totals[name] += dt
                step_seconds += dt
        self.steps += 1
        self.metrics.count("train.steps")
        self.metrics.add(
            "train.step", records=1, seconds=step_seconds,
            latency=step_seconds,
        )
        if self._pending_t0_ns is not None:
            telemetry.record_span(
                "train.step",
                self._pending_t0_ns,
                time.perf_counter_ns() - self._pending_t0_ns,
                step=self.steps,
            )
        self.abort_step()
        if self.steps % self.window == 0:
            self._refresh_window()

    def abort_step(self) -> None:
        """Drop the buffered partial step (the exhaustion-discovery
        iteration): nothing lands in the registry, so every published
        number keeps agreeing with ``train.steps``."""
        self._pending = {p: 0.0 for p in self.PHASES}
        self._pending_t0_ns = None

    def _refresh_window(self) -> None:
        deltas = {
            p: self._totals[p] - self._window_start[p] for p in self.PHASES
        }
        total = sum(deltas.values())
        if total > 0:
            self._last_shares = {p: deltas[p] / total for p in self.PHASES}
            for p, v in self._last_shares.items():
                self.metrics.gauge(
                    telemetry.TRAIN_SHARE_PREFIX + p, round(v, 4)
                )
            telemetry.instant(
                "train.verdict", verdict=self.verdict(), step=self.steps
            )
        self._window_start = dict(self._totals)

    def flush(self) -> None:
        """Publish the shares for a run that never completed one window
        (run_train_loop calls this at loop end). Once a full window HAS
        published, flush is a no-op: republishing a 1-2 step trailing
        remainder would overwrite the windowed gauges — and the spool's
        final snapshot, and the doctor's verdict — with single-step
        noise (one anomalous GC pause or shard-boundary wait)."""
        if self._last_shares:
            return
        if any(
            self._totals[p] > self._window_start[p] for p in self.PHASES
        ):
            self._refresh_window()

    def shares(self) -> Dict[str, float]:
        """The newest windowed shares; before the first full window (or
        for a run shorter than one window), the lifetime shares."""
        if self._last_shares:
            return dict(self._last_shares)
        total = sum(self._totals.values())
        if total <= 0:
            return {}
        return {p: v / total for p, v in self._totals.items()}

    def verdict(self) -> str:
        return telemetry.training_verdict(self.shares())


def fold_model_diagnostics(diag, metrics=None) -> Dict[str, float]:
    """In-jit model diagnostics (models.lm ``diagnostics=True`` output) ->
    the flight recorder: one gauge (last value) + one histogram
    observation (distribution over steps) per metric, so the spool ships
    them to the fleet doctor and ``doctor train`` can print the
    expert-imbalance / bubble lines. Returns the folded floats (the
    caller may log them).

    Gauges: ``moe.dropped_fraction``, ``moe.gate_entropy``,
    ``moe.expert_imbalance`` (max/mean of per-expert routed tokens — 1.0
    = perfectly balanced routing), ``pipeline.bubble_fraction``. Device
    scalars are fetched with float(): call AFTER the step's loss is
    already blocked on, so the fetch adds no sync point of its own."""
    metrics = metrics or METRICS
    out: Dict[str, float] = {}
    if not diag:
        return out
    import numpy as np

    # ONE transfer for the whole tiny pytree: per-field float() would pay
    # a dispatch fence each (measured at >10% step overhead on the bench's
    # small LM; one device_get keeps the A/B within the <=2% bar)
    host = jax.device_get(diag)
    if "expert_tokens" in host:
        tokens = np.asarray(host["expert_tokens"], dtype=float)
        mean = tokens.mean() if tokens.size else 0.0
        out["moe.expert_imbalance"] = (
            float(tokens.max() / mean) if mean > 0 else 0.0
        )
        out["moe.dropped_fraction"] = float(host["dropped_fraction"])
        out["moe.gate_entropy"] = float(host["gate_entropy"])
    if "bubble_fraction" in host:
        out["pipeline.bubble_fraction"] = float(host["bubble_fraction"])
        if float(host.get("virtual_stages", 1)) > 1:
            # the interleaved schedule's number, under its own name so a
            # dashboard can read V>1 runs against the 1F1B baseline
            out["pipeline.bubble_fraction_v"] = float(
                host["bubble_fraction"]
            )
    for name, v in out.items():
        metrics.gauge(name, v)
        metrics.observe(name, v)
    return out


def report_mesh(mesh, metrics=None) -> Dict[str, int]:
    """Publish the trainer's mesh shape as ``train.mesh.<axis>`` gauges
    (axis name -> extent), so the spool ships the parallelism layout to
    the fleet and `tfrecord_doctor train` can print WHICH mesh a trainer
    is flying (a dp×fsdp×pp trainer and a pure-dp one look identical in
    phase shares; they are very different machines). Returns the shape
    dict (the caller may log it)."""
    metrics = metrics or METRICS
    shape = {
        name: int(size)
        for name, size in zip(mesh.axis_names, mesh.devices.shape)
    }
    for name, size in shape.items():
        metrics.gauge(f"train.mesh.{name}", size)
    return shape


def report_fsdp_param_bytes(params, metrics=None) -> int:
    """Per-device AT-REST param bytes of an fsdp-placed tree (sum of each
    leaf's local shard), published as the ``lm.fsdp_param_bytes`` gauge —
    the number the gather-on-use layout exists to shrink, shipped with
    the spool so the fleet doctor sees it next to the mesh shape."""
    import numpy as np

    metrics = metrics or METRICS
    per_dev = sum(
        int(np.prod(p.sharding.shard_shape(p.shape))) * p.dtype.itemsize
        for p in jax.tree.leaves(params)
    )
    metrics.gauge("lm.fsdp_param_bytes", per_dev)
    return per_dev


def trainer_spool(spool_dir: Optional[str] = None, interval_s=None):
    """Acquire this process's telemetry spool under the ``trainer`` role
    (None when no dir is configured). Falls back to the
    ``TFR_TRAIN_SPOOL_DIR`` env var so the no-argparse examples
    (train_dlrm, train_longdoc) spool without growing a CLI; pair with
    ``release_trainer_spool`` so a clean exit lands the ``final: true``
    goodbye snapshot (the aggregator then never flags the trainer dead).
    """
    spool_dir = spool_dir or os.environ.get("TFR_TRAIN_SPOOL_DIR")
    if not spool_dir:
        return None
    from tpu_tfrecord import fleet

    if interval_s is None:
        env = os.environ.get("TFR_TRAIN_SPOOL_INTERVAL_S")
        interval_s = float(env) if env else None
    return fleet.acquire_spool(spool_dir, role="trainer", interval_s=interval_s)


def release_trainer_spool(spool) -> None:
    """Release a ``trainer_spool`` handle (no-op for None)."""
    if spool is not None:
        from tpu_tfrecord import fleet

        fleet.release_spool(spool.spool_dir)


def run_train_loop(
    it,
    produce: Callable,
    step_fn: Callable,
    state: Tuple,
    *,
    save: Optional[Callable[[int, object, object], None]] = None,
    save_every: int = 8,
    log_every: int = 8,
    on_step: Optional[Callable[[int, object], None]] = None,
    max_steps: Optional[int] = None,
    phases: Optional[StepPhases] = None,
) -> Tuple[Tuple, int, DutyCycle]:
    """The shared duty-cycled loop.

    - ``it``: the dataset's batch iterator (supports next(it, None)).
    - ``produce(cb) -> global_batch``: host prep + device placement; runs
      inside the WAIT window — it covers everything the host does between
      steps, including blocking on the prefetch queue, so the duty cycle
      cannot inflate exactly when the input pipeline is the bottleneck.
    - ``step_fn(state, gb) -> (state, loss)``: the jitted update; the
      PREVIOUS loss is blocked inside the busy window (its device time)
      and the next step dispatches async — a one-deep pipeline where host
      prep of batch N+1 overlaps device compute of batch N.
    - ``save(step, it, state)``: checkpoint cadence (every ``save_every``
      steps, aligned with the log line); receives the live train state so
      model checkpoints never need to smuggle it out of the loop.
    - ``on_step(step, loss)``: per-step hook AFTER the loss is known
      (train_lm logs step/digest/loss lines through it).
    - ``phases``: the StepPhases recorder decomposing every step into
      ``train.*`` stages + the windowed training verdict. Always on (one
      is constructed when the caller passes none — pass your own to read
      shares()/verdict() after the run).

    Every completed step records a ``train.step`` flight-recorder span
    (Chrome trace, when tracing is on — exactly one per counted step) and
    is wrapped in a ``tracing.trace`` xprof annotation, so profiler
    timelines carry explicit step markers.

    Returns (state, steps, duty).
    """
    step = 0
    duty = DutyCycle()
    rec = phases if phases is not None else StepPhases()
    prev_loss = None
    while max_steps is None or step < max_steps:
        with trace("train.step"):
            with duty.wait():
                with rec.phase("data_wait", iterator=it):
                    cb = next(it, None)
                with rec.phase("h2d"):
                    gb = produce(cb) if cb is not None else None
            with duty.step():
                with rec.phase("compute"):
                    if prev_loss is not None:
                        jax.block_until_ready(prev_loss)
                    if gb is not None:
                        state, prev_loss = step_fn(state, gb)
            if cb is None:
                # exhaustion discovery, not a step: the drained-pipeline
                # wait must not land in the phase stages or the shares
                rec.abort_step()
                break
            step += 1
            # blocking on THIS step's freshly dispatched loss (the
            # on_step/log paths) is device-step wall time: it must land
            # in the compute phase, or an instrumented run (--diagnostics
            # forces on_step) would report near-zero compute and misread
            # a compute-bound trainer as input_bound
            if on_step is not None and prev_loss is not None:
                with rec.phase("compute"):
                    jax.block_until_ready(prev_loss)
                on_step(step, prev_loss)
            if step % log_every == 0 and prev_loss is not None:
                with rec.phase("compute"):
                    jax.block_until_ready(prev_loss)
                print(f"step {step}  loss ~{float(prev_loss):.4f}", flush=True)
            if save is not None and step % save_every == 0:
                with rec.phase("ckpt"):
                    save(step, it, state)
            rec.end_step()
    if prev_loss is not None:
        jax.block_until_ready(prev_loss)
    rec.flush()  # a run shorter than one window still lands its shares
    return state, step, duty


def finish(
    ckpt_dir: Optional[str],
    step: int,
    batch_size: int,
    t0: float,
    duty: DutyCycle,
    clear_state: bool = True,
    stages: bool = False,
    phases: Optional[StepPhases] = None,
) -> None:
    """End-of-run bookkeeping shared by the examples: clear the input
    state when the epoch budget is exhausted (so the next run starts a
    fresh pass instead of resuming into an empty stream), print the
    examples/s line, the duty cycle, the train-phase shares + verdict
    (when a StepPhases recorder ran), and optionally the stage table."""
    if clear_state and ckpt_dir is not None:
        state_file = checkpoint.state_path(ckpt_dir)
        if os.path.exists(state_file):
            os.remove(state_file)
    dt = time.perf_counter() - t0
    print(f"done: {step} steps, {step * batch_size / dt:,.0f} examples/s")
    if duty.value() is not None:
        print(f"device duty cycle: {duty.value():.1%}")
    if phases is not None and phases.shares():
        shares = {k: round(v, 3) for k, v in phases.shares().items()}
        print(f"train phases: {shares}  verdict: {phases.verdict()}")
    if stages:
        print("stage throughput:", stage_throughput())
