#!/usr/bin/env python
"""End-to-end example: train the causal LM on packed token SequenceExamples.

The trainer that proves the model-parallel layer (ISSUE 10 / ROADMAP #4):
  1. generate token documents (a sparse-bigram synthetic language) as
     SequenceExamples through the io layer
  2. stream them with TFRecordDataset; pack the ragged docs into dense
     [B, L+1] causal batches with TokenPacker (no padding, no masks)
  3. feed the mesh through the double-buffered DeviceIterator
  4. jit train steps whose attention is ZIGZAG CAUSAL RING over the 'seq'
     axis (--mesh dp_sp, default), or whose blocks run as PIPELINE stages
     over the 'pipe' axis (--mesh dp_pp: the dp×pp composed mesh with the
     scale-shaped O(mb) microbatch stream), or plain dp (--mesh dp), or
     with GSPMD WEIGHT SHARDING over the 'fsdp' axis (--mesh dp_fsdp /
     dp_fsdp_pp: params + optimizer state live sharded, gather on use —
     per-device at-rest bytes shrink ~linearly in the fsdp extent)
  5. checkpoint params + optimizer + IteratorState + packer carry in ONE
     atomic file every --save-every steps; kill -9 and rerun to resume —
     the packed-batch stream and the loss curve continue byte-identically
     (tools/verify.sh pins this)
  6. fly the training flight recorder (ISSUE 13): every step decomposes
     into train.data_wait/h2d/compute/ckpt phases with a windowed
     input/compute/ckpt-bound verdict; --spool SPOOL_DIR joins the fleet
     under the trainer role (read it with `tfrecord_doctor train`),
     --trace-out saves a step-marked Chrome trace, and --diagnostics
     folds the in-jit MoE/pipeline diagnostics (expert counts, dropped
     fraction, gate entropy, measured bubble) into gauges each step

Run on any JAX backend; for a local simulation:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_lm.py
"""

import argparse
import functools
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import tpu_tfrecord

# Without this, a dead device tunnel makes backend discovery hang even
# under JAX_PLATFORMS=cpu — see ensure_jax_platform.
tpu_tfrecord.ensure_jax_platform()

import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _harness

from tpu_tfrecord import checkpoint
from tpu_tfrecord.io.dataset import IteratorState, TFRecordDataset
from tpu_tfrecord.io.writer import DatasetWriter
from tpu_tfrecord.models import lm
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.schema import ArrayType, LongType, StructField, StructType
from tpu_tfrecord.tpu import DeviceIterator, TokenPacker, create_mesh

VOCAB = 256
SEQ_LEN = 64
BATCH = 32


def make_schema() -> StructType:
    return StructType([StructField("tokens", ArrayType(LongType()))])


def generate(data_dir: str, shards: int = 4, docs: int = 256) -> None:
    """Token documents from the shared sparse-bigram language, written as
    SequenceExamples in ONE job (sharded via max_records_per_file) so
    _SUCCESS can never cover a partial dataset."""
    if os.path.exists(os.path.join(data_dir, "_SUCCESS")):
        return
    rng = np.random.default_rng(0)
    table = lm.bigram_table(VOCAB, 4)
    rows = []
    for _ in range(shards * docs):
        n = int(rng.integers(16, 97))
        t = int(rng.integers(VOCAB))
        doc = np.empty(n, np.int64)
        for j in range(n):
            doc[j] = t
            t = int(table[t, rng.integers(4)])
        rows.append([doc.tolist()])
    DatasetWriter(
        data_dir,
        make_schema(),
        TFRecordOptions.from_map(recordType="SequenceExample"),
        mode="overwrite",
        max_records_per_file=docs,
    ).write_rows(rows)


def pick_mesh(kind: str, virtual: int = 1):
    """(mesh, cfg axes, n_layers) for the requested parallelism on however
    many devices exist (odd counts degrade to dp). ``virtual`` > 1 picks
    the interleaved dp_pp shape: 2 stages × V round-robin chunks of the
    same 4 layers, cutting the bubble toward (S-1)/(V·M+S-1). The fsdp
    kinds add GSPMD weight sharding: params live sharded over the 'fsdp'
    axis and gather on use (models.lm), so per-device at-rest bytes for
    params + optimizer state shrink ~linearly in the fsdp extent."""
    n_dev = len(jax.devices())
    if kind == "dp_sp" and n_dev % 2 == 0:
        mesh = create_mesh({"data": n_dev // 2, "seq": 2})
        return mesh, {"data_axis": "data", "seq_axis": "seq"}, 2
    if kind == "dp_fsdp" and n_dev % 2 == 0:
        mesh = create_mesh({"data": 2, "fsdp": n_dev // 2})
        return mesh, {"data_axis": "data", "fsdp_axis": "fsdp"}, 2
    if kind == "dp_fsdp_pp" and n_dev % 8 == 0:
        mesh = create_mesh({"pipe": 2, "data": 2, "fsdp": n_dev // 4})
        return mesh, {
            "data_axis": "data", "pipe_axis": "pipe", "fsdp_axis": "fsdp",
        }, 4
    if kind == "dp_fsdp_pp" and n_dev % 4 == 0:
        mesh = create_mesh({"pipe": 2, "data": 1, "fsdp": n_dev // 2})
        return mesh, {
            "data_axis": "data", "pipe_axis": "pipe", "fsdp_axis": "fsdp",
        }, 4
    if kind == "dp_pp" and virtual > 1 and n_dev % 2 == 0:
        mesh = create_mesh({"pipe": 2, "data": n_dev // 2})
        return mesh, {"data_axis": "data", "pipe_axis": "pipe"}, 4
    if kind == "dp_pp" and n_dev % 4 == 0:
        mesh = create_mesh({"pipe": 4, "data": n_dev // 4})
        return mesh, {"data_axis": "data", "pipe_axis": "pipe"}, 4
    if kind == "dp_pp" and n_dev % 2 == 0:
        mesh = create_mesh({"pipe": 2, "data": n_dev // 2})
        return mesh, {"data_axis": "data", "pipe_axis": "pipe"}, 4
    mesh = create_mesh({"data": n_dev})
    return mesh, {"data_axis": "data"}, 2


class LMCheckpoint:
    """Params + optimizer + input position + packer carry, saved together.

    Now the async npz-shard twin (ISSUE 16): a thin wrapper over
    ``checkpoint.AsyncCheckpointer``, so the caller's thread only pays
    for the device snapshot while the stage+fsync+rename commit and the
    manifest-last generation layout run on the background commit thread.
    A kill -9 at any point resumes from the newest COMPLETE generation —
    the same pairing guarantee the old single-file ``os.replace`` gave,
    plus durability (fsync) and an off-step-path disk. ``sync=True`` is
    the measurement twin: identical bytes, commit inline on the caller's
    thread (what the bench A/B and verify.sh throttle legs compare).
    Still numpy+stdlib on the persistence side — orbax stays optional.
    """

    def __init__(self, directory: str, *, sync: bool = False):
        self.directory = directory
        self._ck = checkpoint.AsyncCheckpointer(
            directory, keep=2, process_index=0, process_count=1, sync=sync,
        )

    def save(self, step: int, state, payload: dict) -> None:
        self._ck.save(step, state, payload)

    def load(self, template):
        """(step, state, payload) or (None, template, None)."""
        return self._ck.restore(template)

    def latest_step(self):
        return self._ck.latest_step()

    def clear(self) -> None:
        """Drop every generation (the epoch-budget-exhausted path)."""
        self._ck.clear()

    def wait(self) -> None:
        self._ck.wait()

    def close(self) -> None:
        self._ck.close()


def packed_stream(it, packer: TokenPacker, snaps: dict):
    """Columnar batches -> packed host batches; records, for packed batch
    n, the (IteratorState, packer carry, digest) snapshot that resumes the
    stream at batch n+1. The DeviceIterator runs this at most one batch
    ahead, so ``snaps`` stays small (pruned to the last 16)."""
    n = 0
    while True:
        b = packer.pop()
        while b is None:
            cb = next(it, None)
            if cb is None:
                return
            packer.feed_column(cb["tokens"])
            b = packer.pop()
        snaps[n] = {
            "input": it.state().to_json(),
            "packer": packer.state(),
            "digest": hashlib.sha256(
                np.ascontiguousarray(b).tobytes()
            ).hexdigest()[:16],
        }
        for old in [k for k in snaps if k < n - 16]:
            del snaps[old]
        yield {"tokens": b}
        n += 1


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mesh", default=os.environ.get("LM_MESH", "dp_sp"),
                    choices=("dp", "dp_sp", "dp_pp", "dp_fsdp",
                             "dp_fsdp_pp"))
    ap.add_argument("--steps", type=int, default=64,
                    help="total train steps (absolute, incl. resumed)")
    ap.add_argument("--save-every", type=int, default=8)
    ap.add_argument("--ckpt-mode", default=os.environ.get(
                        "TFR_CKPT_MODE", "async"),
                    choices=("async", "sync"),
                    help="async (default): background commit, the train "
                         "loop only pays for the device snapshot; sync: "
                         "the measurement twin, commit inline on the "
                         "step path (what made ckpt_bound verdicts)")
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--digest-out", default=None,
                    help="write one {'step','digest','loss'} JSON line per "
                         "step (the kill/resume byte-identity evidence)")
    ap.add_argument("--data-dir", default="/tmp/tpu_tfrecord_lm/data")
    ap.add_argument("--ckpt-dir", default="/tmp/tpu_tfrecord_lm/ckpt")
    ap.add_argument("--spool", default=None, metavar="SPOOL_DIR",
                    help="spool this trainer's telemetry (role=trainer) "
                         "into SPOOL_DIR for TelemetryAggregator / "
                         "`tfrecord_doctor train`/`fleet`")
    ap.add_argument("--spool-interval", type=float, default=None,
                    metavar="SECONDS", help="spool snapshot cadence")
    ap.add_argument("--trace-out", default=None, metavar="FILE",
                    help="enable the flight recorder and save the Chrome "
                         "trace (train.step spans + phase markers) here")
    ap.add_argument("--diagnostics", action="store_true",
                    help="in-jit model diagnostics: MoE expert counts/"
                         "drops/entropy and the measured pipeline bubble, "
                         "folded into gauges+histograms each step")
    ap.add_argument("--moe", type=int, default=0, metavar="EXPERTS",
                    help="swap every block's FFN for a top-2 MoE with "
                         "this many experts (0 = dense; dp/dp_sp only)")
    ap.add_argument("--virtual", type=int, default=1, choices=(1, 2),
                    metavar="V", help="interleaved virtual stages for "
                    "--mesh dp_pp: V round-robin layer chunks per device "
                    "(models.pipeline), bubble -> (S-1)/(V*M+S-1)")
    args = ap.parse_args()

    if args.virtual > 1 and args.mesh != "dp_pp":
        ap.error("--virtual > 1 needs --mesh dp_pp")
    generate(args.data_dir)
    mesh, axes, n_layers = pick_mesh(args.mesh, args.virtual)
    if args.moe and "pipe_axis" in axes:
        ap.error("--moe is not supported with --mesh dp_pp")
    cfg = lm.LMConfig(
        vocab_size=VOCAB, d_model=64, n_heads=4, n_layers=n_layers,
        max_len=SEQ_LEN, n_micro=8 if "pipe_axis" in axes else None,
        moe_experts=args.moe,
        n_virtual=args.virtual if "pipe_axis" in axes else 1,
    )
    print(f"mesh: {_harness.report_mesh(mesh)} mode={args.mesh}")

    params = lm.init_params(jax.random.key(0), cfg)
    tx = optax.adam(3e-3)
    opt_state = tx.init(params)
    os.makedirs(args.ckpt_dir, exist_ok=True)
    ck = LMCheckpoint(args.ckpt_dir, sync=(args.ckpt_mode == "sync"))
    start_step, (params, opt_state), payload = ck.load((params, opt_state))
    placement = {
        k: axes[k] for k in ("pipe_axis", "fsdp_axis") if k in axes
    }
    if placement:
        # at-rest sharding: P(pipe) stage slicing and/or P(fsdp) weight
        # sharding; the restored host tree places under ANY layout — the
        # checkpoint itself is layout-free (tests/test_lm_fsdp.py pins
        # the interchange)
        params = jax.device_put(
            params, lm.param_shardings(mesh, params, **placement)
        )
        if "fsdp_axis" in placement:
            per_dev = _harness.report_fsdp_param_bytes(params)
            print(f"fsdp param bytes/device: {per_dev}")
    if start_step is None:
        start_step = 0
        print("fresh start")
    else:
        print(f"resumed at step {start_step}")

    ds = TFRecordDataset(
        args.data_dir, batch_size=64, schema=make_schema(),
        num_epochs=args.epochs, recordType="SequenceExample",
        shuffle=True, seed=0,
    )
    resume = (
        IteratorState.from_json(payload["input"]) if payload else None
    )
    packer = TokenPacker(BATCH, SEQ_LEN)
    if payload:
        packer.restore(payload["packer"])

    step_jit = jax.jit(
        functools.partial(
            lm.train_step, cfg=cfg, tx=tx, mesh=mesh,
            diagnostics=args.diagnostics, **axes,
        ),
        donate_argnums=(0, 1),
    )
    snaps: dict = {}
    digest_fh = open(args.digest_out, "a") if args.digest_out else None  # graftlint: allow(atomic-write: append-only one-line-per-step digest log; the kill -9 tests tolerate a torn tail line)
    last_diag: dict = {}

    def step_fn(state, gb):
        p, o = state
        if args.diagnostics:
            p, o, loss, diag = step_jit(p, o, gb["tokens"])
            last_diag["diag"] = diag
        else:
            p, o, loss = step_jit(p, o, gb["tokens"])
        return (p, o), loss

    def save(rel_step, _it, state):
        snap = snaps.get(rel_step - 1)  # stream position AFTER that batch
        if snap is None:
            return
        ck.save(
            start_step + rel_step, state,
            {"input": snap["input"], "packer": snap["packer"]},
        )

    def on_step(rel_step, loss):
        step = start_step + rel_step
        snap = snaps.get(rel_step - 1, {})
        line = {
            "step": step,
            "digest": snap.get("digest"),
            "loss": repr(float(loss)),
        }
        print("lm_step", json.dumps(line), flush=True)
        if digest_fh is not None:
            digest_fh.write(json.dumps(line) + "\n")
            digest_fh.flush()

    def fold_step(rel_step, loss):
        # the loss is already blocked on: fetching the tiny diag dict
        # adds no sync point of its own
        diag = last_diag.pop("diag", None)
        if diag is not None:
            _harness.fold_model_diagnostics(diag)
        if digest_fh is not None:
            on_step(rel_step, loss)

    if args.trace_out:
        from tpu_tfrecord import telemetry

        telemetry.enable()
    spool = _harness.trainer_spool(args.spool, args.spool_interval)
    phases = _harness.StepPhases()
    t0 = time.perf_counter()
    try:
        with ds.batches(resume) as it:
            with DeviceIterator(
                packed_stream(it, packer, snaps), mesh, axis=axes["data_axis"]
            ) as dev_it:
                (params, opt_state), steps, duty = _harness.run_train_loop(
                    dev_it,
                    produce=lambda gb: gb,  # DeviceIterator already placed it
                    step_fn=step_fn,
                    state=(params, opt_state),
                    save=save,
                    save_every=args.save_every,
                    on_step=(
                        fold_step
                        if (args.diagnostics or digest_fh is not None)
                        else None
                    ),
                    max_steps=(
                        args.steps - start_step if args.steps else None
                    ),
                    phases=phases,
                )
        if digest_fh is not None:
            digest_fh.close()
        ck.wait()  # drain the in-flight commit before judging completion
        completed = args.steps and start_step + steps >= args.steps
        if not completed:
            # the epoch budget is exhausted: next run starts a fresh pass
            ck.clear()
        if args.trace_out:
            from tpu_tfrecord import telemetry

            telemetry.RECORDER.save_chrome_trace(args.trace_out)
            print(f"trace saved: {args.trace_out}")
        _harness.finish(
            None, start_step + steps, BATCH, t0, duty, clear_state=False,
            stages=True, phases=phases,
        )
    finally:
        ck.close()  # drain the background commit thread
        # a clean exit lands the spool's `final: true` goodbye snapshot
        _harness.release_trainer_spool(spool)


if __name__ == "__main__":
    main()
