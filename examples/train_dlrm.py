#!/usr/bin/env python
"""End-to-end example: train the DLRM consumer on TFRecord data.

Covers the whole framework surface:
  1. generate a Criteo-like TFRecord dataset (columnar native encode)
  2. stream it with TFRecordDataset (native decode, prefetch, shuffle)
  3. hash categoricals, pack columns, assemble global sharded batches
  4. jit train steps over the mesh; checkpoint the input position
  5. resume from the saved state

Run on any JAX backend; for a local simulation:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_dlrm.py
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import tpu_tfrecord

# Without this, a dead device tunnel makes backend discovery hang even
# under JAX_PLATFORMS=cpu (verified) — see ensure_jax_platform.
tpu_tfrecord.ensure_jax_platform()

import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _harness

from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.models import DLRMConfig, init_params, train_step
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType
from tpu_tfrecord.serde import TFRecordSerializer, encode_row
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.tpu import create_mesh, host_batch_from_columnar, make_global_batch

NUM_DENSE, NUM_CAT = 13, 26
VOCAB = 1 << 16
BATCH = 1024


def make_schema() -> StructType:
    fields = [StructField("label", LongType(), nullable=False)]
    fields += [StructField(f"I{i}", LongType()) for i in range(NUM_DENSE)]
    fields += [StructField(f"C{i}", StringType()) for i in range(NUM_CAT)]
    return StructType(fields)


def generate(data_dir: str, shards: int = 4, rows: int = 4096) -> None:
    if os.path.exists(os.path.join(data_dir, "_SUCCESS")):
        return
    schema = make_schema()
    ser = TFRecordSerializer(schema)
    rng = np.random.default_rng(0)

    def all_rows():
        for _ in range(shards * rows):
            row = [int(rng.integers(0, 2))]
            row += [int(v) for v in rng.integers(0, 1 << 20, size=NUM_DENSE)]
            row += [f"v{int(v)}" for v in rng.integers(0, 5000, size=NUM_CAT)]
            yield encode_row(ser, RecordType.EXAMPLE, row)

    from tpu_tfrecord import wire

    os.makedirs(data_dir, exist_ok=True)
    it = all_rows()
    for s in range(shards):
        wire.write_records(
            os.path.join(data_dir, f"part-{s:05d}-gen.tfrecord"),
            (next(it) for _ in range(rows)),
        )
    open(os.path.join(data_dir, "_SUCCESS"), "wb").close()  # graftlint: allow(atomic-write: zero-byte marker; no content to tear)


def main() -> None:
    data_dir = "/tmp/tpu_tfrecord_example/data"
    ckpt_dir = "/tmp/tpu_tfrecord_example/ckpt"
    generate(data_dir)
    schema = make_schema()

    mesh = create_mesh()
    cfg = DLRMConfig(
        num_dense=NUM_DENSE, num_categorical=NUM_CAT, vocab_size=VOCAB, embed_dim=16
    )
    params = init_params(jax.random.key(0), cfg)
    tx = optax.adam(1e-3)
    if os.environ.get("DLRM_SPARSE", "0") == "1":
        # Sparse embedding updates (row-wise AdaGrad on touched rows only):
        # the table gradient never materializes, which is what makes real
        # Criteo vocabularies (2^20+ rows/table) trainable — see
        # models.dlrm.sparse_train_step. Adam still drives the MLPs.
        from tpu_tfrecord.models import sparse_opt_init, sparse_train_step

        opt_state = sparse_opt_init(params, cfg, tx)
        step_fn = jax.jit(
            functools.partial(sparse_train_step, cfg=cfg, tx=tx), donate_argnums=(0, 1)
        )
    else:
        opt_state = tx.init(params)
        step_fn = jax.jit(functools.partial(train_step, cfg=cfg, tx=tx), donate_argnums=(0, 1))

    hash_buckets = {f"C{i}": VOCAB for i in range(NUM_CAT)}
    pack = {
        "dense": [f"I{i}" for i in range(NUM_DENSE)],
        "cat": [f"C{i}" for i in range(NUM_CAT)],
    }

    # NOTE: in a real job the input state is saved/restored TOGETHER with the
    # model checkpoint (params/opt_state) at the same step — here only the
    # input position is persisted, to keep the example focused on the data
    # pipeline (train_lm.py shows the atomic combined checkpoint).
    ds = TFRecordDataset(
        data_dir, batch_size=BATCH, schema=schema, num_epochs=2,
        # two-scale mixing: seeded shard-order shuffle + windowed row
        # shuffle (rows permute across 8-batch windows; resume-exact)
        shuffle=True, shuffle_window=8, seed=0
    )

    def produce(cb):
        hb = host_batch_from_columnar(
            cb, ds.schema, hash_buckets=hash_buckets, pack=pack
        )
        # standard Criteo dense preprocessing: log(1+x)
        hb["dense"] = np.log1p(hb["dense"].clip(min=0)).astype(np.float32)
        hb["label"] = hb["label"].astype(np.float32)
        return make_global_batch(hb, mesh)

    def step(state, gb):
        params, opt_state = state
        params, opt_state, loss = step_fn(params, opt_state, gb)
        return (params, opt_state), loss

    # TFR_TRAIN_SPOOL_DIR spools this trainer (role=trainer) for the
    # fleet doctor; the step-phase recorder runs regardless
    spool = _harness.trainer_spool()
    phases = _harness.StepPhases()
    t0 = time.perf_counter()
    it, _resume = _harness.resume_or_fresh(ds, ckpt_dir)
    save_cb, saver = _harness.state_saver(ckpt_dir)
    try:
        with it:
            (params, opt_state), steps, duty = _harness.run_train_loop(
                it, produce, step, (params, opt_state),
                save=save_cb,
                phases=phases,
            )
        saver.wait()  # drain the background commit before the summary
        _harness.finish(
            ckpt_dir, steps, BATCH, t0, duty, stages=True, phases=phases
        )
    finally:
        saver.close()
        _harness.release_trainer_spool(spool)


if __name__ == "__main__":
    main()
