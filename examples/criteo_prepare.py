#!/usr/bin/env python
"""Prepare Criteo click-log TSV data as TFRecord shards.

The dataset-prep half of the pipeline: raw Criteo TSV (label \\t 13 integer
features \\t 26 hex categorical features, empty field = missing) becomes
TFRecord shards written through the native columnar encoder — the same
files bench.py and examples/train_dlrm.py then stream into the TPU.

Usage:
    python examples/criteo_prepare.py [input.tsv] [output_dir]

With no arguments it generates a small synthetic TSV first (demo mode).
ColumnarBatches are built straight from parsed numpy columns (values +
validity masks) — no per-row Example objects anywhere.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from tpu_tfrecord.columnar import Column, ColumnarBatch
from tpu_tfrecord.io.writer import DatasetWriter
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType

NUM_DENSE, NUM_CAT = 13, 26
CHUNK_ROWS = 50_000


def criteo_schema() -> StructType:
    fields = [StructField("label", LongType(), nullable=False)]
    fields += [StructField(f"I{i}", LongType()) for i in range(1, NUM_DENSE + 1)]
    fields += [StructField(f"C{i}", StringType()) for i in range(1, NUM_CAT + 1)]
    return StructType(fields)


def rows_to_batch(lines) -> ColumnarBatch:
    """Parse TSV lines into a ColumnarBatch (values + masks, no rows)."""
    import itertools

    split = [ln.rstrip("\n").split("\t") for ln in lines]
    n = len(split)
    # one transpose instead of 40 per-column passes with bounds checks
    columns = list(itertools.zip_longest(*split, fillvalue=""))
    columns += [("",) * n] * (1 + NUM_DENSE + NUM_CAT - len(columns))
    labels_raw = columns[0]
    bad = next((i for i, v in enumerate(labels_raw) if not v.lstrip("-").isdigit()), None)
    if bad is not None:
        raise ValueError(
            f"bad label {labels_raw[bad]!r} in line: {lines[bad].rstrip()[:80]!r}"
        )
    cols = {}
    cols["label"] = Column(
        "label",
        LongType(),
        values=np.array([int(v) for v in labels_raw], dtype=np.int64),
        mask=np.ones(n, dtype=bool),
    )
    for i in range(NUM_DENSE):
        raw = columns[1 + i]
        mask = np.array([v != "" for v in raw], dtype=bool)
        vals = np.array([int(v) if v != "" else 0 for v in raw], dtype=np.int64)
        cols[f"I{i+1}"] = Column(f"I{i+1}", LongType(), values=vals, mask=mask)
    for i in range(NUM_CAT):
        raw = columns[1 + NUM_DENSE + i]
        mask = np.array([v != "" for v in raw], dtype=bool)
        col = Column(f"C{i+1}", StringType(), mask=mask)
        col.set_blobs([v.encode() for v in raw])
        cols[f"C{i+1}"] = col
    return ColumnarBatch(cols, n)


def generate_demo_tsv(path: str, rows: int = 20_000) -> None:
    rng = np.random.default_rng(0)
    with open(path, "w") as fh:  # graftlint: allow(atomic-write: demo input generator; a torn file is re-generated, never served)
        for _ in range(rows):
            parts = [str(int(rng.integers(0, 2)))]
            for _ in range(NUM_DENSE):
                parts.append(
                    "" if rng.random() < 0.1 else str(int(rng.integers(0, 10_000)))
                )
            for _ in range(NUM_CAT):
                parts.append(
                    "" if rng.random() < 0.05 else f"{int(rng.integers(0, 1 << 32)):08x}"
                )
            fh.write("\t".join(parts) + "\n")


def prepare(tsv_path: str, out_dir: str) -> None:
    schema = criteo_schema()
    writer = DatasetWriter(
        out_dir,
        schema,
        TFRecordOptions(),
        mode="overwrite",
        max_records_per_file=500_000,
    )

    def batches():
        with open(tsv_path) as fh:
            chunk = []
            for line in fh:
                if not line.strip():
                    continue  # tolerate stray blank lines
                chunk.append(line)
                if len(chunk) >= CHUNK_ROWS:
                    yield rows_to_batch(chunk)
                    chunk = []
            if chunk:
                yield rows_to_batch(chunk)

    files = writer.write_batches(batches())
    print(f"wrote {len(files)} shard(s) to {out_dir}")


def main() -> None:
    if len(sys.argv) >= 3:
        tsv, out = sys.argv[1], sys.argv[2]
    elif len(sys.argv) == 2:
        tsv = sys.argv[1]
        out = tsv + ".tfrecords"
        print(f"no output dir given; writing to {out}")
    else:
        base = "/tmp/tpu_tfrecord_criteo"
        os.makedirs(base, exist_ok=True)
        tsv = os.path.join(base, "demo.tsv")
        out = os.path.join(base, "tfrecords")
        if not os.path.exists(tsv):
            print("demo mode: generating synthetic Criteo TSV ...")
            generate_demo_tsv(tsv)
    prepare(tsv, out)

    # sanity: stream it back the way training would
    schema = criteo_schema()
    from tpu_tfrecord.io.dataset import TFRecordDataset

    ds = TFRecordDataset(out, batch_size=4096, schema=schema, drop_remainder=False)
    total = 0
    missing_I1 = 0
    with ds.batches() as it:
        for cb in it:
            total += cb.num_rows
            missing_I1 += int((~cb["I1"].mask).sum())
    print(f"read back {total} records; I1 missing in {missing_I1} ({missing_I1/total:.1%})")


if __name__ == "__main__":
    main()
