#!/usr/bin/env python
"""Write-path benchmark: Criteo-shaped columnar batches -> TFRecord shards.

The materialization half of the BASELINE.md north-star: examples/sec
serialized + framed (CRC32C) + codec-compressed + committed to disk through
DatasetWriter.write_batches, for the same Criteo-shaped schema bench.py
ingests (int64 label, 13 int64 dense, 26 categorical byte strings).

Measures the sequential legacy path (write_workers=1) and the parallel slab
pipeline (write_workers=N, num_shards=S) for both uncompressed and zlib
output, and prints ONE JSON line in bench.py's shape: {"metric", "value",
"unit", "vs_baseline"} where value is the parallel rate for the default
codec and vs_baseline is value / 1e6.

Methodology (this is a SHARED box — same discipline as bench.py):
- sequential and parallel reps are INTERLEAVED and each side reports its
  best-of (one-sided noise: other tenants only slow a rep down);
- ``parallel_scaling_probe`` is measured first: the wall-clock scaling of
  two plain threads running zlib.compress concurrently (GIL released, no
  pipeline) — the box's attainable parallel ceiling. On a host with P real
  cores this approaches min(P, workers); on SMT-shared or host-contended
  vCPUs it can be well under 2, and then NO writer can reach 2x. The
  disclosed ``speedup_vs_attainable`` (speedup / probe) is the pipeline's
  efficiency against that ceiling.

Env knobs: TFR_BENCH_WRITE_WORKERS (4), TFR_BENCH_WRITE_SHARDS (4),
TFR_BENCH_WRITE_CODEC (zlib; 'none' for uncompressed headline),
TFR_BENCH_WRITE_BATCH (16384), TFR_BENCH_WRITE_BATCHES (6),
TFR_BENCH_WRITE_REPS (3 interleaved pairs), TFR_BENCH_WRITE_DIR.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from bench import criteo_schema

BATCH = int(os.environ.get("TFR_BENCH_WRITE_BATCH", 16384))
N_BATCHES = int(os.environ.get("TFR_BENCH_WRITE_BATCHES", 6))
WORKERS = int(os.environ.get("TFR_BENCH_WRITE_WORKERS", 4))
SHARDS = int(os.environ.get("TFR_BENCH_WRITE_SHARDS", 4))
REPS = int(os.environ.get("TFR_BENCH_WRITE_REPS", 3))
CODEC = os.environ.get("TFR_BENCH_WRITE_CODEC", "zlib")
CAT_LEN = 8  # bytes per categorical value (matches bench.py's generator)


def make_batches(schema):
    """Criteo-shaped ColumnarBatches built directly from numpy buffers (no
    per-row Python) so the benchmark measures the writer, not the setup."""
    from tpu_tfrecord.columnar import Column, ColumnarBatch

    rng = np.random.default_rng(0)
    batches = []
    cat_offsets = np.arange(BATCH + 1, dtype=np.int64) * CAT_LEN
    for _ in range(N_BATCHES):
        cols = {}
        cols["label"] = Column(
            "label", schema["label"].data_type,
            values=rng.integers(0, 2, size=BATCH, dtype=np.int64),
        )
        for i in range(1, 14):
            name = f"I{i}"
            cols[name] = Column(
                name, schema[name].data_type,
                values=rng.integers(0, 1 << 31, size=BATCH, dtype=np.int64),
            )
        for i in range(1, 27):
            name = f"C{i}"
            blob = (
                rng.integers(0, 16, size=BATCH * CAT_LEN, dtype=np.uint8) + 97
            ).tobytes()
            cols[name] = Column(
                name, schema[name].data_type,
                blob=blob, blob_offsets=cat_offsets,
            )
        batches.append(ColumnarBatch(cols, BATCH))
    return batches


def parallel_scaling_probe() -> float:
    """Attainable 2-thread scaling for GIL-free compression on this box:
    wall(1 thread doing 2N units) / wall(2 threads doing N units each).
    2.0 = two real unshared cores; ~1.0 = no parallelism to win."""
    import zlib

    data = os.urandom(4 << 20)
    n = 3

    def spin(count):
        for _ in range(count):
            zlib.compress(data)

    spin(1)  # warm
    t0 = time.perf_counter()
    spin(2 * n)
    serial = time.perf_counter() - t0
    threads = [threading.Thread(target=spin, args=(n,)) for _ in range(2)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dual = time.perf_counter() - t0
    return serial / dual


def run_once(schema, batches, out_dir, codec, workers, num_shards, trace="off"):
    """One full write_batches job (encode + frame + compress + commit);
    returns (examples/sec, METRICS 'write' family snapshot, occupancy).
    ``occupancy`` is the slab pipeline's in-flight fill EMA (None for the
    sequential path) — telemetry.boundness_verdict reads it as
    committer-bound (high) vs encode-bound (low)."""
    from tpu_tfrecord.io.writer import DatasetWriter
    from tpu_tfrecord.metrics import METRICS
    from tpu_tfrecord.options import TFRecordOptions

    opts = TFRecordOptions.from_map(
        codec=None if codec in (None, "none") else codec,
        write_workers=workers,
        num_shards=num_shards,
        trace=trace,
    )
    n_examples = sum(b.num_rows for b in batches)
    METRICS.reset()
    writer = DatasetWriter(out_dir, schema, opts, mode="overwrite")
    t0 = time.perf_counter()
    writer.write_batches(batches)
    rate = n_examples / (time.perf_counter() - t0)
    stages = METRICS.snapshot("write")
    occupancy = METRICS.gauge_value("write.occupancy")
    shutil.rmtree(out_dir, ignore_errors=True)
    return rate, stages, occupancy


def measure_pair(schema, batches, out_dir, codec):
    """Interleaved best-of-REPS for sequential vs parallel under the same
    ambient load; returns (seq_best, par_best, par_best_stages, par_occ)."""
    run_once(schema, batches, out_dir, codec, 1, None)  # warm both paths
    run_once(schema, batches, out_dir, codec, WORKERS, SHARDS)
    seq_best, par_best, par_stages, par_occ = 0.0, 0.0, {}, None
    for _ in range(REPS):
        seq, _, _ = run_once(schema, batches, out_dir, codec, 1, None)
        par, stages, occ = run_once(
            schema, batches, out_dir, codec, WORKERS, SHARDS
        )
        seq_best = max(seq_best, seq)
        if par > par_best:
            par_best, par_stages, par_occ = par, stages, occ
    return seq_best, par_best, par_stages, par_occ


def tracing_overhead(schema, batches, out_dir, codec):
    """Flight-recorder overhead on the parallel write path: interleaved
    trace-off/trace-on reps, best-of-each (one-sided noise — same argument
    as the read bench). Returns the overhead pct (negative = in the
    noise)."""
    from tpu_tfrecord import telemetry as tm

    off_best, on_best = 0.0, 0.0
    for r in range(REPS):
        order = (("off",), ("on",)) if r % 2 == 0 else (("on",), ("off",))
        for (mode,) in order:
            if mode == "on":
                tm.RECORDER.clear()
            rate, _, _ = run_once(
                schema, batches, out_dir, codec, WORKERS, SHARDS, trace=mode
            )
            tm.disable()
            if mode == "on":
                on_best = max(on_best, rate)
            else:
                off_best = max(off_best, rate)
    tm.RECORDER.clear()
    return round((1.0 - on_best / off_best) * 100.0, 2) if off_best else None


def main() -> None:
    from tpu_tfrecord.telemetry import boundness_verdict, quantiles_ms

    schema = criteo_schema()
    batches = make_batches(schema)
    work_dir = os.environ.get("TFR_BENCH_WRITE_DIR") or tempfile.mkdtemp(
        prefix="tpu_tfrecord_bench_write_"
    )
    out_dir = os.path.join(work_dir, "out")
    probe = parallel_scaling_probe()
    results, breakdowns, quantiles, occupancies = {}, {}, {}, {}
    for codec in ("none", "zlib"):
        seq, par, stages, occ = measure_pair(schema, batches, out_dir, codec)
        results[codec] = (seq, par)
        # gauges share the snapshot namespace with distinct shapes — only
        # stage entries carry "seconds"
        breakdowns[codec] = {
            name: round(st["seconds"], 3)
            for name, st in sorted(stages.items())
            if "seconds" in st
        }
        quantiles[codec] = quantiles_ms(stages)
        occupancies[codec] = occ
    trace_pct = tracing_overhead(schema, batches, out_dir, "zlib")
    shutil.rmtree(work_dir, ignore_errors=True)

    headline = {"": "none", "none": "none", "zlib": "zlib", "deflate": "zlib"}.get(
        CODEC
    )
    if headline is None:
        raise SystemExit(
            f"TFR_BENCH_WRITE_CODEC={CODEC!r} is not measured by this bench "
            "(supported: none, zlib/deflate)"
        )
    seq, par = results[headline]
    speedup = par / seq if seq else None
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        cores = os.cpu_count() or 1
    out = {
        "metric": "criteo_tf_example_write_to_disk",
        "value": round(par, 1),
        "unit": "examples/sec/host",
        # same normalization as bench.py's read-side headline (>=1M ex/s)
        "vs_baseline": round(par / 1_000_000, 4),
        "codec": None if headline == "none" else "deflate",
        "write_workers": WORKERS,
        "num_shards": SHARDS,
        "examples": BATCH * N_BATCHES,
        "seq_value": round(seq, 1),
        "speedup": round(speedup, 2) if speedup else None,
        # the box's measured parallel ceiling and our efficiency against it:
        # 2 unshared cores -> probe ~2.0 and speedup reads directly against
        # the >=2x target; SMT/host-contended vCPUs cap the probe (and any
        # writer) below that
        "cores": cores,
        "parallel_scaling_probe": round(probe, 2),
        "speedup_vs_attainable": round(speedup / probe, 2) if speedup else None,
        "uncompressed_value": round(results["none"][1], 1),
        "uncompressed_seq_value": round(results["none"][0], 1),
        "uncompressed_speedup": round(
            results["none"][1] / results["none"][0], 2
        ) if results["none"][0] else None,
        "zlib_value": round(results["zlib"][1], 1),
        "zlib_seq_value": round(results["zlib"][0], 1),
        "zlib_speedup": round(
            results["zlib"][1] / results["zlib"][0], 2
        ) if results["zlib"][0] else None,
        # per-stage wall seconds of the best parallel rep (worker stages sum
        # across threads, so encode+compress can exceed the job wall time —
        # that overlap is the point)
        "breakdown_seconds": breakdowns[headline],
        # flight-recorder A/B on the parallel path (ISSUE 5 acceptance:
        # <= 2%; negative = in the noise)
        "tracing_overhead_pct": trace_pct,
        # per-stage latency quantiles (always-on histograms) + the write
        # pipeline's bound-ness: "consumer_bound" = the committer (IO) is
        # the bottleneck, "producer_bound" = encode/planner is
        "telemetry": {
            "quantiles": quantiles[headline],
            "write_occupancy": (
                round(occupancies[headline], 4)
                if occupancies[headline] is not None
                else None
            ),
            "verdict": boundness_verdict(occupancies[headline]),
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
