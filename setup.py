"""Build hook: compile the native fast path into the package.

`python setup.py build_native` (or any build that triggers it) produces
tpu_tfrecord/_lib/libtfrecord_native.so via g++. The library is optional —
tpu_tfrecord._native also compiles it lazily on first use, and every code
path has a pure-Python fallback — so build failures are non-fatal.
"""

import subprocess
import sys

from setuptools import Command, setup


class BuildNative(Command):
    description = "compile tpu_tfrecord/csrc/tfrecord_native.cc into tpu_tfrecord/_lib/"
    user_options = []

    def initialize_options(self):
        pass

    def finalize_options(self):
        pass

    def run(self):
        import os
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from tpu_tfrecord import _native

        if _native.available():
            print(f"native library built: {_native._LIB_PATH}")
        else:
            print(f"native build unavailable: {_native.load_error()}", file=sys.stderr)


setup(cmdclass={"build_native": BuildNative})
